"""tracectl: fetch a request's span timeline from the HTTP frontend and
pretty-print it as an ASCII waterfall (or save Chrome trace-event JSON).

    python -m dynamo_tpu.cli.tracectl <request_id> \
        [--url http://127.0.0.1:8080] [--chrome out.json] [--json]
    python -m dynamo_tpu.cli.tracectl --list [--url ...]
    python -m dynamo_tpu.cli.tracectl decisions [--limit N] [--json]
    python -m dynamo_tpu.cli.tracectl --bundle incident.json \
        [--chrome out.json] [--json]

The request id is the ``x-request-id`` response header every frontend
response carries. ``--chrome`` writes Perfetto-loadable trace-event JSON
(open at https://ui.perfetto.dev or chrome://tracing).

``decisions`` prints the KV router's decision audit
(``GET /v1/router/decisions``): one line per routed request with the
chosen worker and each candidate's overlap/cache_usage/load score terms.

``--bundle FILE`` consumes an exported incident bundle
(``ctl incident export``) entirely OFFLINE — no frontend needed: the
retro-assembled trace renders as the usual waterfall, ``--chrome`` emits
Perfetto JSON from it, and the per-process ring/stall summary prints
alongside.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from typing import Any, Dict, List

from ..utils.dynconfig import EnvDefaultsParser

BAR_WIDTH = 40


def _fetch_json(url: str) -> Any:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _fmt_dur(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:8.3f}s "
    if seconds >= 1e-3:
        return f"{seconds * 1e3:8.2f}ms"
    return f"{seconds * 1e6:8.1f}us"


def render_timeline(spans: List[Dict[str, Any]], width: int = BAR_WIDTH
                    ) -> str:
    """ASCII waterfall of one trace's spans (pure function; unit-tested).

    Spans are drawn in start order, indented by parent depth, with a
    proportional ``[###]`` bar positioned on the trace's wall-clock extent
    and per-span component/duration/status columns."""
    if not spans:
        return "(no spans)"
    spans = sorted(spans, key=lambda s: (s.get("start") or 0.0,
                                         s.get("end") or 0.0))
    t0 = min(s.get("start") or 0.0 for s in spans)
    t1 = max(s.get("end") or 0.0 for s in spans)
    total = max(t1 - t0, 1e-9)
    by_id = {s.get("span_id"): s for s in spans}

    def depth(s, guard=0) -> int:
        p = s.get("parent_id")
        if p is None or p not in by_id or guard > 16:
            return 0
        return 1 + depth(by_id[p], guard + 1)

    name_w = max(len("  " * depth(s) + s.get("name", "?")) for s in spans)
    name_w = min(max(name_w, 12), 48)
    comp_w = max((len(f"{s.get('component', '?')}:{s.get('pid', 0)}")
                  for s in spans), default=8)
    lines = [f"trace {spans[0].get('trace_id', '?')} — {len(spans)} spans, "
             f"{_fmt_dur(total).strip()} total"]
    for s in spans:
        start = (s.get("start") or 0.0) - t0
        dur = max(0.0, (s.get("end") or 0.0) - (s.get("start") or 0.0))
        lo = int(round(start / total * width))
        hi = int(round((start + dur) / total * width))
        hi = min(max(hi, lo + 1), width)
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        label = ("  " * depth(s) + s.get("name", "?"))[:name_w]
        comp = f"{s.get('component', '?')}:{s.get('pid', 0)}"
        err = "  !ERROR" if s.get("status") not in (None, "ok") else ""
        lines.append(f"{label:<{name_w}} |{bar}| {_fmt_dur(dur)} "
                     f"{comp:<{comp_w}}{err}")
    return "\n".join(lines)


def render_decisions(decisions: List[Dict[str, Any]]) -> str:
    """One line per audited routing decision (pure function; unit-tested):
    chosen worker + the per-candidate ``logit=2*ovl-usage-load`` terms."""
    if not decisions:
        return "(no routing decisions recorded)"
    lines = [f"{len(decisions)} routing decisions (oldest first)"]
    for d in decisions:
        wid = d.get("worker_id")
        chosen = f"{wid:x}" if wid is not None else "WAITED"
        retries = f" retries={d['retries']}" if d.get("retries") else ""
        salt = f" salt={d['salt']:x}" if d.get("salt") else ""
        lines.append(
            f"#{d.get('seq', '?')} isl={d.get('isl_tokens', '?')}tok/"
            f"{d.get('isl_blocks', '?')}blk{salt} -> {chosen} "
            f"(ovl={d.get('overlap_blocks', 0)}blk){retries}")
        for c in d.get("candidates", []):
            mark = "*" if c.get("worker_id") == wid else " "
            sat = "  SATURATED" if c.get("saturated") else ""
            lines.append(
                f"   {mark} {c['worker_id']:x}: logit={c['logit']:+.4f} "
                f"(ovl={c['overlap_norm']:.2f} usage={c['cache_usage']:.2f}"
                f" load={c['load']:.2f}){sat}")
    return "\n".join(lines)


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dynamo-tracectl")
    p.add_argument("request_id", nargs="?", default=None,
                   help="trace/request id (x-request-id response header), "
                        "or the literal 'decisions' for the router audit")
    p.add_argument("--limit", type=int, default=0,
                   help="decisions: max entries to fetch (0 = ring size)")
    p.add_argument("--url", default="http://127.0.0.1:8080",
                   help="frontend base URL")
    p.add_argument("--list", action="store_true",
                   help="list recent trace ids instead")
    p.add_argument("--json", action="store_true",
                   help="dump the raw span JSON instead of the waterfall")
    p.add_argument("--chrome", default=None, metavar="FILE",
                   help="write Chrome trace-event JSON to FILE")
    p.add_argument("--bundle", default=None, metavar="FILE",
                   help="read an exported incident bundle instead of a "
                        "frontend (offline; see `ctl incident export`)")
    return p.parse_args(argv)


def run_bundle(args) -> int:
    """Offline incident-bundle mode: summary + trace waterfall (or
    --chrome / --json) from the exported file alone."""
    from ..obs.incidents import bundle_summary
    from ..utils.tracing import Span, merge_spans, to_chrome_trace

    with open(args.bundle) as f:
        bundle = json.load(f)
    if args.json:
        print(json.dumps(bundle["trace"], indent=2))
        return 0
    if args.chrome:
        # the trigger's retro-assembled trace plus EVERY process's ring
        # spans: a manual/SIGUSR2 capture has no trigger trace, but its
        # rings still hold the last window of activity per process
        groups = [[Span.from_dict(d) for d in bundle.get("trace", [])]]
        for snap in bundle.get("processes", {}).values():
            ring = snap.get("rings", {}).get("spans", {}).get("items", [])
            groups.append([Span.from_dict(d) for d in ring])
        chrome = to_chrome_trace(merge_spans(*groups))
        with open(args.chrome, "w") as f:
            json.dump(chrome, f)
        print(f"wrote {len(chrome.get('traceEvents', []))} events to "
              f"{args.chrome} (load in https://ui.perfetto.dev)")
        return 0
    for line in bundle_summary(bundle):
        print(line)
    if bundle.get("trace"):
        print()
        print(render_timeline(bundle["trace"]))
    return 0


def run(args) -> int:
    base = args.url.rstrip("/")
    try:
        if args.bundle:
            return run_bundle(args)
        if args.list:
            data = _fetch_json(f"{base}/v1/traces")
            for tid in data.get("traces", []):
                print(tid)
            return 0
        if not args.request_id:
            print("error: request_id required (or --list)", file=sys.stderr)
            return 2
        if args.request_id == "decisions":
            data = _fetch_json(
                f"{base}/v1/router/decisions?limit={args.limit}")
            if args.json:
                print(json.dumps(data, indent=2))
            else:
                print(render_decisions(data.get("decisions", [])))
            return 0
        if args.chrome:
            chrome = _fetch_json(
                f"{base}/v1/traces/{args.request_id}?format=chrome")
            with open(args.chrome, "w") as f:
                json.dump(chrome, f)
            print(f"wrote {len(chrome.get('traceEvents', []))} events to "
                  f"{args.chrome} (load in https://ui.perfetto.dev)")
            return 0
        data = _fetch_json(f"{base}/v1/traces/{args.request_id}")
        if args.json:
            print(json.dumps(data, indent=2))
        else:
            print(render_timeline(data.get("spans", [])))
        return 0
    except urllib.error.HTTPError as e:
        print(f"error: {e.code} {e.reason} for {e.url}", file=sys.stderr)
        return 1
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def main() -> None:
    raise SystemExit(run(parse_args()))


if __name__ == "__main__":
    main()
