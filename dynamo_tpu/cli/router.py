"""Standalone KV-aware router service.

    python -m dynamo_tpu.cli.router --namespace dynamo --worker-component \
        backend --store 127.0.0.1:4222

Serves ``route`` on {namespace}/router: {token_ids} -> {worker_id}.
Reference capability: components/router/src/main.rs.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import logging

from ..llm.kv_router.router import FleetKvRouter, KvRouterService
from ..runtime.component import DistributedRuntime

log = logging.getLogger("dynamo_tpu.router")


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-router")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="router")
    p.add_argument("--worker-component", default="backend")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--advertise-host", default=None)
    p.add_argument("--block-size", type=int, default=64)
    p.add_argument("--fleet", action="store_true",
                   help="route for every model in the fleet registry "
                        "(fleet_models/) instead of one worker "
                        "component; requests dispatch on their 'model' "
                        "field with per-model candidate sets")
    return p.parse_args(argv)


async def run_router(args, *, ready_event=None,
                     drt: DistributedRuntime | None = None) -> None:
    host, port = args.store.split(":")
    own = drt is None
    if own:
        drt = await DistributedRuntime(
            store_host=host, store_port=int(port),
            advertise_host=args.advertise_host).connect()
    # getattr: harnesses build the Namespace by hand (sdk serving graph)
    fleet = getattr(args, "fleet", False)
    if fleet:
        svc = FleetKvRouter(drt, args.namespace,
                            block_size=args.block_size)
    else:
        svc = KvRouterService(drt, args.namespace, args.worker_component,
                              block_size=args.block_size)
    # fleet brownout level: any level above normal switches the scheduler
    # to fast-fail instead of capacity-wait polling (utils/overload.py).
    # Armed BEFORE start so fleet mode hands the shared state to every
    # per-model router it creates.
    from ..utils.overload import BrownoutState

    try:
        svc.brownout = await BrownoutState().watch(drt.store, args.namespace)
    except Exception:
        log.warning("brownout watch failed; router stays in wait mode",
                    exc_info=True)
    await svc.start()
    await svc.serve(drt.namespace(args.namespace).component(args.component))
    # flight recorder + watchdog + incident coordination: an incident
    # bundle gets this router's decision-ring slice — WHY the wedged /
    # torn-stream request landed on that worker is part of the black box
    from .. import obs

    obs_handle = await obs.start_process(
        "router", store=drt.store, namespace=args.namespace,
        proc_label=f"router:{drt.worker_id:x}")
    obs_handle.manager.add_source("router_decisions",
                                  lambda: svc.decisions(0))
    # publish this process's stage registry (dyn_kv_cluster_hits_total,
    # histogram series the audit plane reads) onto the standard
    # metrics_stage/ merge path — a router that only *made* decisions
    # would keep its cluster-hit counter invisible to /metrics and dyntop
    from ..llm.metrics_aggregator import StagePublisher

    stage_pub = StagePublisher(drt.store, args.namespace, args.component,
                               drt.worker_id, drt.lease)

    async def stage_publish_loop():
        while True:
            try:
                await stage_pub.publish()
            except Exception:
                log.debug("router stage publish skipped", exc_info=True)
            await asyncio.sleep(2.0)

    stage_task = asyncio.create_task(stage_publish_loop())
    print(f"kv router serving {args.namespace}.{args.component}.route "
          f"(workers: {'<fleet registry>' if fleet else args.worker_component})",
          flush=True)
    if ready_event is not None:
        ready_event.set()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        stage_task.cancel()
        await obs_handle.stop()
        await svc.stop()
        if own:
            await drt.close()


def main() -> None:
    from ..utils.logging_ext import init_logging
    init_logging()
    try:
        asyncio.run(run_router(parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
