"""llmctl equivalent: manage model -> endpoint registrations in the store.

    python -m dynamo_tpu.cli.ctl --store 127.0.0.1:4222 http add chat \
        my-model dynamo.backend.generate [--model-path ...]
    python -m dynamo_tpu.cli.ctl http list
    python -m dynamo_tpu.cli.ctl http remove chat my-model
    python -m dynamo_tpu.cli.ctl disagg set --namespace dynamo \
        --max-local-prefill-length 1000 --max-prefill-queue-size 2

Reference capability: launch/llmctl (http add/list/remove model mappings)
plus live disagg-threshold reconfiguration (the reference's etcd-watched
DisaggregatedRouter config, lib/llm/src/disagg_router.rs:38-143).
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json

from ..llm.model_card import ModelDeploymentCard
from ..llm.remote import list_models, register_model, unregister_model
from ..runtime.store_client import StoreClient


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-ctl")
    p.add_argument("--store", default="127.0.0.1:4222")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http")
    hsub = http.add_subparsers(dest="action", required=True)

    add = hsub.add_parser("add")
    add.add_argument("model_type", choices=("chat", "completion", "both"))
    add.add_argument("name")
    add.add_argument("endpoint", help="ns.component.endpoint")
    add.add_argument("--model-path", default=None)
    add.add_argument("--kv-block-size", type=int, default=64)

    rem = hsub.add_parser("remove")
    rem.add_argument("model_type", choices=("chat", "completion", "both"))
    rem.add_argument("name")

    hsub.add_parser("list")

    disagg = sub.add_parser("disagg")
    dsub = disagg.add_subparsers(dest="action", required=True)
    dset = dsub.add_parser("set")
    dset.add_argument("--namespace", default="dynamo")
    dset.add_argument("--model", default="default")
    dset.add_argument("--max-local-prefill-length", type=int, default=1000)
    dset.add_argument("--max-prefill-queue-size", type=int, default=2)
    dget = dsub.add_parser("get")
    dget.add_argument("--namespace", default="dynamo")
    dget.add_argument("--model", default="default")
    return p.parse_args(argv)


async def run(args) -> int:
    host, port = args.store.split(":")
    store = await StoreClient(host, int(port)).connect()
    try:
        if args.plane == "disagg":
            from ..llm.disagg import (DisaggConfig, disagg_config_key,
                                      set_disagg_config)

            if args.action == "set":
                cfg = DisaggConfig(
                    max_local_prefill_length=args.max_local_prefill_length,
                    max_prefill_queue_size=args.max_prefill_queue_size)
                await set_disagg_config(store, args.namespace, cfg,
                                        model=args.model)
                print(f"disagg config for {args.namespace}/{args.model}: "
                      f"{cfg.to_dict()}")
            else:
                raw = await store.get(
                    disagg_config_key(args.namespace, args.model))
                print(raw.decode() if raw else "(not set)")
            return 0
        if args.action == "add":
            if args.model_path:
                card = ModelDeploymentCard.resolve(args.model_path, args.name)
            else:
                card = ModelDeploymentCard.synthetic(args.name)
            card.kv_block_size = args.kv_block_size
            types = (["chat", "completion"] if args.model_type == "both"
                     else [args.model_type])
            for t in types:
                await register_model(store, card, args.endpoint, model_type=t)
            print(f"added {args.name} -> {args.endpoint} ({','.join(types)})")
        elif args.action == "remove":
            types = (["chat", "completion"] if args.model_type == "both"
                     else [args.model_type])
            for t in types:
                await unregister_model(store, args.name, model_type=t)
            print(f"removed {args.name}")
        elif args.action == "list":
            for m in await list_models(store):
                inst = (f"  x{m['instances']}"
                        if m.get("instances", 1) > 1 else "")
                print(f"{m['type']:<11} {m['name']:<30} {m['endpoint']}"
                      f"{inst}")
        return 0
    finally:
        await store.close()


def main() -> None:
    raise SystemExit(asyncio.run(run(parse_args())))


if __name__ == "__main__":
    main()
