"""llmctl equivalent: manage model -> endpoint registrations in the store.

    python -m dynamo_tpu.cli.ctl --store 127.0.0.1:4222 http add chat \
        my-model dynamo.backend.generate [--model-path ...]
    python -m dynamo_tpu.cli.ctl http list
    python -m dynamo_tpu.cli.ctl http remove chat my-model
    python -m dynamo_tpu.cli.ctl disagg set --namespace dynamo \
        --max-local-prefill-length 1000 --max-prefill-queue-size 2

Reference capability: launch/llmctl (http add/list/remove model mappings)
plus live disagg-threshold reconfiguration (the reference's etcd-watched
DisaggregatedRouter config, lib/llm/src/disagg_router.rs:38-143).
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json

from ..llm.model_card import ModelDeploymentCard
from ..llm.remote import list_models, register_model, unregister_model
from ..runtime.scale.shards import make_store_client
from ..runtime.store_client import StoreClient


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-ctl")
    p.add_argument("--store", default="127.0.0.1:4222")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http")
    hsub = http.add_subparsers(dest="action", required=True)

    add = hsub.add_parser("add")
    add.add_argument("model_type", choices=("chat", "completion", "both"))
    add.add_argument("name")
    add.add_argument("endpoint", help="ns.component.endpoint")
    add.add_argument("--model-path", default=None)
    add.add_argument("--kv-block-size", type=int, default=64)

    rem = hsub.add_parser("remove")
    rem.add_argument("model_type", choices=("chat", "completion", "both"))
    rem.add_argument("name")

    hsub.add_parser("list")

    disagg = sub.add_parser("disagg")
    dsub = disagg.add_subparsers(dest="action", required=True)
    dset = dsub.add_parser("set")
    dset.add_argument("--namespace", default="dynamo")
    dset.add_argument("--model", default="default")
    dset.add_argument("--max-local-prefill-length", type=int, default=1000)
    dset.add_argument("--max-prefill-queue-size", type=int, default=2)
    dget = dsub.add_parser("get")
    dget.add_argument("--namespace", default="dynamo")
    dget.add_argument("--model", default="default")

    # fleet plane: the desired-state model registry (fleet_models/)
    fleet = sub.add_parser("fleet")
    fsub = fleet.add_subparsers(dest="action", required=True)
    fadd = fsub.add_parser("add")
    fadd.add_argument("name")
    fadd.add_argument("--namespace", default="dynamo")
    fadd.add_argument("--component", default=None,
                      help="worker component for this model's pool "
                           "(default: backend-<name>)")
    fadd.add_argument("--engine", default="jax")
    fadd.add_argument("--model-path", default=None)
    fadd.add_argument("--chips", type=int, default=1,
                      help="chips per replica (0 = exempt from the "
                           "global chip budget)")
    fadd.add_argument("--min-replicas", type=int, default=0,
                      help="replica floor (0 allows scale-to-zero)")
    fadd.add_argument("--max-replicas", type=int, default=4)
    fadd.add_argument("--priority", type=int, default=0,
                      help="arbitration rank: higher takes chips first")
    fadd.add_argument("--tenant", action="append", default=[],
                      metavar="TENANT:rps=R,burst=B,concurrency=C",
                      help="per-tenant quota entry (repeatable), e.g. "
                           "--tenant acme:rps=5,burst=10,concurrency=8")
    fadd.add_argument("--worker-args", default="",
                      help="extra args for spawned workers, "
                           "space-separated")
    fadd.add_argument("--swap-group", default="",
                      help="model-mobility swap class: models sharing a "
                           "group hot-swap into each other on preemption "
                           "(in-place weight swap, no cold spawn)")
    fadd.add_argument("--prewarm", action="store_true",
                      help="every worker in the namespace stages this "
                           "model's weights into its host cache (wake "
                           "by swap even across swap groups)")
    frem = fsub.add_parser("remove")
    frem.add_argument("name")
    frem.add_argument("--namespace", default="dynamo")
    flist = fsub.add_parser("list")
    flist.add_argument("--namespace", default="dynamo")

    # incident plane: flight-recorder capture beacons + assembled bundles
    inc = sub.add_parser("incident")
    isub = inc.add_subparsers(dest="action", required=True)
    icap = isub.add_parser("capture",
                           help="publish a manual capture beacon: every "
                                "live process dumps its rings")
    icap.add_argument("--namespace", default="dynamo")
    icap.add_argument("--reason", default="manual")
    icap.add_argument("--trace-id", default=None,
                      help="retro-assemble this trace into the bundle "
                           "(sampled-out spans included)")
    icap.add_argument("--window", type=float, default=30.0,
                      help="seconds of ring history before now to freeze")
    ils = isub.add_parser("ls")
    ils.add_argument("--namespace", default="dynamo")
    ishow = isub.add_parser("show")
    ishow.add_argument("incident_id")
    ishow.add_argument("--namespace", default="dynamo")
    iexp = isub.add_parser("export")
    iexp.add_argument("incident_id")
    iexp.add_argument("--namespace", default="dynamo")
    iexp.add_argument("-o", "--out", default=None,
                      help="output file (default <incident_id>.json); "
                           "feed to `tracectl --bundle`")

    # byte-flow ledger: the per-link matrix every worker publishes
    fl = sub.add_parser("flows",
                        help="cluster byte-flow ledger: per-link bytes, "
                             "bandwidth and saturation, hottest first")
    fl.add_argument("--namespace", default="dynamo")
    fl.add_argument("--limit", type=int, default=0,
                    help="show at most N links (0 = all)")
    fl.add_argument("--kind", default=None,
                    help="only links that moved this flow kind "
                         "(e.g. disagg_push, kvpage_pagein)")
    fl.add_argument("--json", action="store_true", dest="as_json",
                    help="raw JSON instead of the table")
    return p.parse_args(argv)


def parse_tenant_quota(entry: str):
    """``acme:rps=5,burst=10,concurrency=8`` -> ("acme", TenantQuota)."""
    from ..utils.overload import TenantQuota

    tenant, _, rest = entry.partition(":")
    if not tenant or not rest:
        raise SystemExit(f"--tenant {entry!r}: expected "
                         f"TENANT:rps=R[,burst=B][,concurrency=C]")
    fields = {}
    for part in rest.split(","):
        key, _, val = part.partition("=")
        if key not in ("rps", "burst", "concurrency") or not val:
            raise SystemExit(f"--tenant {entry!r}: unknown field {part!r}")
        try:
            fields[key] = float(val)
        except ValueError:
            raise SystemExit(f"--tenant {entry!r}: {key}={val!r} is not "
                             f"a number")
    return tenant, TenantQuota(
        rps=fields.get("rps", 0.0), burst=fields.get("burst", 0.0),
        concurrency=int(fields.get("concurrency", 0)))


async def run(args) -> int:
    host, port = args.store.split(":")
    store = await make_store_client(host, int(port)).connect()
    try:
        if args.plane == "incident":
            return await run_incident(store, args)
        if args.plane == "flows":
            return await run_flows(store, args)
        if args.plane == "fleet":
            from ..fleet.registry import (FleetModelSpec, fetch_fleet_status,
                                          list_fleet_models,
                                          put_fleet_model,
                                          remove_fleet_model)

            if args.action == "add":
                card = None
                if args.model_path:
                    card = ModelDeploymentCard.resolve(
                        args.model_path, args.name).to_dict()
                spec = FleetModelSpec(
                    name=args.name, component=args.component or "",
                    engine=args.engine, model_path=args.model_path,
                    chips_per_replica=args.chips,
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas,
                    priority=args.priority,
                    tenants=dict(parse_tenant_quota(t)
                                 for t in args.tenant),
                    card=card,
                    extra_args=[a for a in args.worker_args.split() if a],
                    swap_group=args.swap_group, prewarm=args.prewarm)
                await put_fleet_model(store, args.namespace, spec)
                print(f"fleet add {args.name}: component="
                      f"{spec.component} chips/replica={spec.chips_per_replica} "
                      f"replicas=[{spec.min_replicas},{spec.max_replicas}] "
                      f"priority={spec.priority} "
                      f"tenants={sorted(spec.tenants) or '-'}"
                      + (f" swap_group={spec.swap_group}"
                         if spec.swap_group else "")
                      + (" prewarm" if spec.prewarm else ""))
            elif args.action == "remove":
                await remove_fleet_model(store, args.namespace, args.name)
                print(f"fleet remove {args.name}: the planner drains its "
                      f"pool on the next tick")
            elif args.action == "list":
                specs = await list_fleet_models(store, args.namespace)
                status = await fetch_fleet_status(store, args.namespace)
                if not specs:
                    print(f"(no fleet models registered in "
                          f"{args.namespace!r})")
                for s in specs:
                    st = status.get(s.name, {})
                    wake = ""
                    if st.get("wake_path"):
                        wake = (f" wake={st['wake_path']}"
                                f"/{st.get('wake_seconds', '?')}s")
                    print(f"{s.name:<24} {s.component:<20} "
                          f"state={st.get('state', 'unreconciled'):<10} "
                          f"replicas={st.get('replicas', '?')}/"
                          f"[{s.min_replicas},{s.max_replicas}] "
                          f"chips={st.get('chips', '?')} "
                          f"prio={s.priority} "
                          f"burn={st.get('burn', '?')} "
                          f"tenants={sorted(s.tenants) or '-'}"
                          + (f" group={s.swap_group}"
                             if s.swap_group else "") + wake)
            return 0
        if args.plane == "disagg":
            from ..llm.disagg import (DisaggConfig, disagg_config_key,
                                      set_disagg_config)

            if args.action == "set":
                cfg = DisaggConfig(
                    max_local_prefill_length=args.max_local_prefill_length,
                    max_prefill_queue_size=args.max_prefill_queue_size)
                await set_disagg_config(store, args.namespace, cfg,
                                        model=args.model)
                print(f"disagg config for {args.namespace}/{args.model}: "
                      f"{cfg.to_dict()}")
            else:
                raw = await store.get(
                    disagg_config_key(args.namespace, args.model))
                print(raw.decode() if raw else "(not set)")
            return 0
        if args.action == "add":
            if args.model_path:
                card = ModelDeploymentCard.resolve(args.model_path, args.name)
            else:
                card = ModelDeploymentCard.synthetic(args.name)
            card.kv_block_size = args.kv_block_size
            types = (["chat", "completion"] if args.model_type == "both"
                     else [args.model_type])
            for t in types:
                await register_model(store, card, args.endpoint, model_type=t)
            print(f"added {args.name} -> {args.endpoint} ({','.join(types)})")
        elif args.action == "remove":
            types = (["chat", "completion"] if args.model_type == "both"
                     else [args.model_type])
            for t in types:
                await unregister_model(store, args.name, model_type=t)
            print(f"removed {args.name}")
        elif args.action == "list":
            for m in await list_models(store):
                inst = (f"  x{m['instances']}"
                        if m.get("instances", 1) > 1 else "")
                print(f"{m['type']:<11} {m['name']:<30} {m['endpoint']}"
                      f"{inst}")
        return 0
    finally:
        await store.close()


async def run_flows(store, args) -> int:
    """Fold every worker's published stage dump into the cluster's
    per-link byte-flow matrix — the same data `dyntop` renders as
    ``links:`` and the frontend serves at ``GET /v1/flows``."""
    from ..llm.metrics_aggregator import fetch_stage_states
    from ..obs.flows import flows_from_states, fmt_bytes

    states = await fetch_stage_states(store, args.namespace)
    links = flows_from_states(states)
    if args.kind:
        links = [e for e in links if args.kind in (e.get("kinds") or {})]
    if args.limit > 0:
        links = links[:args.limit]
    if args.as_json:
        print(json.dumps({"links": links, "count": len(links)},
                         indent=1, sort_keys=True))
        return 0
    if not links:
        print(f"(no flows published in {args.namespace!r})")
        return 0
    print(f"{'link':<28} {'bytes':>10} {'bw':>12} {'sat':>6} "
          f"{'cong':>5}  kinds")
    for e in links:
        kinds = " ".join(
            f"{k}={fmt_bytes(v)}" for k, v in sorted(
                (e.get("kinds") or {}).items(), key=lambda kv: -kv[1]))
        print(f"{e['src'] + '>' + e['dst']:<28} "
              f"{fmt_bytes(float(e.get('bytes') or 0)):>10} "
              f"{float(e.get('bw') or 0.0) / 1e6:>10.1f}MB "
              f"{float(e.get('saturation') or 0.0):>6.2f} "
              f"{int(e.get('congested') or 0):>5}  {kinds}")
    return 0


async def run_incident(store, args) -> int:
    from ..obs import incidents as _incidents

    if args.action == "capture":
        beacon = await _incidents.publish_beacon(
            store, args.namespace, args.reason, window_s=args.window,
            trace_id=args.trace_id, by="ctl")
        print(f"incident {beacon['id']} captured: every live process is "
              f"dumping its rings\n  inspect: ctl incident show "
              f"{beacon['id']}")
        return 0
    if args.action == "ls":
        beacons = await _incidents.list_incidents(store, args.namespace)
        if not beacons:
            print(f"(no live incidents in {args.namespace!r})")
            return 0
        import time as _time
        for b in beacons:
            age = _time.time() - b.get("at", 0.0)
            tid = b.get("trace_id") or "-"
            print(f"{b['id']:<40} {b['reason']:<16} age={age:>6.0f}s "
                  f"trace={tid}  by={b.get('by', '?')}")
        return 0
    bundle = await _incidents.fetch_bundle(store, args.namespace,
                                           args.incident_id)
    if bundle is None:
        print(f"no incident {args.incident_id!r} (expired or never "
              f"captured)")
        return 1
    if args.action == "show":
        for line in _incidents.bundle_summary(bundle):
            print(line)
        return 0
    # export: the offline bundle tracectl --bundle consumes
    out = args.out or f"{args.incident_id}.json"
    with open(out, "w") as f:
        json.dump(bundle, f, indent=1, sort_keys=True)
    print(f"incident {args.incident_id} -> {out} "
          f"({len(bundle['processes'])} process dumps, "
          f"{len(bundle['trace'])} trace spans)")
    return 0


def main() -> None:
    raise SystemExit(asyncio.run(run(parse_args())))


if __name__ == "__main__":
    main()
