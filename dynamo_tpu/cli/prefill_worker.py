"""Prefill worker: pulls the shared prefill queue, computes prompt KV on its
own TPU slice, ships it to the owning decode worker.

    python -m dynamo_tpu.cli.prefill_worker --namespace dynamo \
        --decode-component backend --store localhost:4222 [--model-path ...]

Like the reference's PrefillWorker (examples/llm/components/
prefill_worker.py:46-158), prefill workers need **no registration**: they are
queue consumers, so scaling up/down is just starting/stopping processes —
unacked jobs are redelivered if one dies mid-prefill.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json
import logging
import time
from typing import Optional

from ..llm.disagg import PrefillQueue
from ..llm.kv_transfer import KV_RECEIVE_ENDPOINT, push_kv, push_kv_error
from ..llm.model_card import ModelDeploymentCard
from ..llm.protocols.common import BackendInput
from ..runtime.component import DistributedRuntime
from ..runtime.engine import Context
from ..utils import tracing

MAX_ATTEMPTS = 3
PREFILL_COMPONENT = "prefill"   # stage-metrics component tag

log = logging.getLogger("dynamo_tpu.prefill_worker")


async def run_prefill_worker(args, *,
                             ready_event: Optional[asyncio.Event] = None,
                             drt: Optional[DistributedRuntime] = None,
                             max_jobs: Optional[int] = None,
                             token=None) -> None:
    host, port = args.store.split(":")
    own_drt = drt is None
    if own_drt:
        drt = await DistributedRuntime(
            store_host=host, store_port=int(port),
            advertise_host=args.advertise_host).connect()
    if token is not None:
        def _lease_lost(lease: int) -> None:
            log.critical("liveness lease %x unrecoverably lost; "
                         "shutting down", lease)
            token.cancel()
        drt.store.on_lease_lost = _lease_lost
    ns = drt.namespace(args.namespace)

    from ..engine.engine import JaxEngine, JaxEngineConfig

    if args.model_path:
        card = ModelDeploymentCard.resolve(args.model_path, args.model_name)
    else:
        card = ModelDeploymentCard.synthetic(args.model_name or "prefill")
    card.kv_block_size = args.kv_block_size
    extra = json.loads(args.extra_engine_args) if args.extra_engine_args else {}
    cfg = JaxEngineConfig.from_card(card, tensor_parallel=args.tp, **extra)
    # off-loop: engine bring-up must not starve the lease keepalive
    engine = await asyncio.get_running_loop().run_in_executor(
        None, lambda: JaxEngine(cfg))

    queue = PrefillQueue(drt.store, args.namespace)
    kv_client = await ns.component(args.decode_component) \
        .endpoint(KV_RECEIVE_ENDPOINT).client().start()

    # tracing + stage metrics: spans flush to the store (the frontend's
    # /v1/traces stitches them); histogram dumps refresh under our lease
    tracing.configure(component="prefill_worker")
    span_sink = await tracing.StoreSpanSink(drt.store).start()

    # flight recorder + watchdog + incident coordination (see cli/worker):
    # a prefill stall or torn push shows up in THIS process's rings, and a
    # beacon raised anywhere in the cluster captures our slice too
    from .. import obs

    obs_handle = await obs.start_process(
        "prefill_worker", store=drt.store, namespace=args.namespace,
        proc_label=f"prefill_worker:{drt.worker_id:x}",
        span_sink=span_sink, install_signal=token is not None)
    from ..llm.metrics_aggregator import StagePublisher

    publisher = StagePublisher(drt.store, args.namespace,
                               PREFILL_COMPONENT, drt.worker_id, drt.lease)

    async def stage_metrics_loop():
        while True:
            try:
                await publisher.publish()
            except Exception:
                log.exception("stage metrics publish failed")
            await asyncio.sleep(1.0)

    stage_task = asyncio.create_task(stage_metrics_loop())

    log.info("prefill worker up, pulling %s", queue.queue)
    print(f"prefill worker pulling {queue.queue}", flush=True)
    if ready_event is not None:
        ready_event.set()
    done = 0
    try:
        while max_jobs is None or done < max_jobs:
            # race the (possibly long-parked) queue pull against drain: a
            # SIGTERM'd prefill worker must stop TAKING jobs immediately —
            # an abandoned pull's message is requeued when the connection
            # closes (at-least-once)
            pull = asyncio.ensure_future(queue.dequeue())
            if token is not None or drt.draining.is_set():
                waiters = {pull, asyncio.ensure_future(drt.draining.wait())}
                if token is not None:
                    waiters.add(asyncio.ensure_future(token.wait()))
                # unbounded-ok: drain/cancel always completes this wait
                await asyncio.wait(waiters,
                                   return_when=asyncio.FIRST_COMPLETED)
                for w in waiters:
                    if w is not pull:
                        w.cancel()
                if not pull.done():
                    pull.cancel()
                    log.info("draining: queue pull stopped")
                    break
            msg_id, job = await pull
            if await queue.consume_cancelled(job.request_id):
                await queue.ack(msg_id)
                log.info("dropping cancelled prefill job %s", job.request_id)
                done += 1
                continue
            # all spans of this job parent under the decode worker's span
            # (carried in job.trace); fallback: stitch by request id
            job_parent = tracing.extract_wire(job.trace, job.request_id)
            ctx = None
            try:
                from ..utils import faults

                # chaos hook: a stalled/failed prefill worker — the decode
                # side's deadline-bounded KV wait must turn this into a 504
                await faults.fire("prefill.compute")
                bi = BackendInput.from_dict(job.request)
                ctx = Context(job.request_id, deadline=job.deadline)
                # register with the runtime so the Worker shell's drain
                # waits for (then stops/kills) the in-flight compute+push
                # instead of cancelling it mid-job — the job must be acked
                # or requeued, never silently half-done
                drt._active[ctx.id] = ctx
                async with tracing.get_tracer().span(
                        "prefill.compute", parent=job_parent,
                        request_id=job.request_id,
                        prompt_tokens=len(bi.token_ids)) as csp:
                    compute_t0 = time.monotonic()
                    k, v, tok, logp = await engine.prefill_extract(bi, ctx)
                    # pure per-item compute cost, published for operators
                    # (the decode side's predictive shed runs on its own
                    # depth-normalized turnaround EWMA)
                    from ..utils.prometheus import stage_metrics

                    stage_metrics().stage_service.observe(
                        "prefill", value=time.monotonic() - compute_t0)
                if await queue.consume_cancelled(job.request_id):
                    # submitter gave up mid-compute: skip the (large) push
                    await queue.ack(msg_id)
                    log.info("dropping cancelled prefill job %s post-compute",
                             job.request_id)
                    done += 1
                    continue
                with tracing.current_span_var_scope(
                        csp.context() if csp is not None else job_parent):
                    await push_kv(kv_client, job.decode_worker_id,
                                  job.request_id, tok, logp, k, v,
                                  src_worker=drt.worker_id)
                await queue.ack(msg_id)
                log.info("prefilled %s (%d tokens) -> worker %x",
                         job.request_id, len(bi.token_ids),
                         job.decode_worker_id)
            except Exception as e:
                # the store only redelivers unacked jobs when THIS connection
                # dies — so ack and explicitly re-enqueue with an attempt
                # count, dead-lettering back to the decode worker when the
                # job looks poisoned (it falls back / errors the request)
                log.exception("prefill job %s failed (attempt %d)",
                              job.request_id, job.attempts + 1)
                job.attempts += 1
                await queue.ack(msg_id)
                if job.attempts < MAX_ATTEMPTS:
                    # restamp so queue-wait measures THIS attempt's wait,
                    # not wait + failed compute + backoff since the first.
                    # Bounds are NOT re-enforced: the job was already
                    # admitted once — a retry must not be shed by a queue
                    # that filled up behind it
                    job.enqueued_at = 0.0
                    await queue.enqueue(job, enforce_bounds=False)
                else:
                    try:
                        await push_kv_error(kv_client, job.decode_worker_id,
                                            job.request_id, str(e))
                    except Exception:
                        log.exception("could not dead-letter %s",
                                      job.request_id)
                await asyncio.sleep(0.2)
            finally:
                if ctx is not None:
                    drt._active.pop(ctx.id, None)
            done += 1
    finally:
        stage_task.cancel()
        queue.close()   # cancel parked per-priority pulls
        await obs_handle.stop()
        try:
            await span_sink.stop()   # final flush: short-lived runs
        except Exception:            # (max_jobs) must not lose spans
            log.warning("span sink final flush failed; tail spans lost",
                        exc_info=True)
        # deregistration: drop the published stage dump so aggregators
        # stop rendering this worker when a shared runtime outlives it
        from ..llm.metrics_aggregator import clear_worker_keys

        await clear_worker_keys(drt.store, args.namespace,
                                PREFILL_COMPONENT, drt.worker_id)
        engine.shutdown()
        if own_drt:
            await drt.close()


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dynamo-prefill-worker")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--decode-component", default="backend")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--advertise-host", default=None)
    p.add_argument("--model-path", default=None)
    p.add_argument("--model-name", default=None)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--kv-block-size", type=int, default=64)
    p.add_argument("--extra-engine-args", default=None,
                   help="inline JSON engine kwargs")
    return p.parse_args(argv)


def main() -> None:
    from ..utils.logging_ext import init_logging
    init_logging()
    args = parse_args()
    # Worker shell: SIGINT/SIGTERM drain gracefully — stop pulling the
    # queue, finish/ship the in-flight job, revoke the lease, exit
    from ..runtime.worker import Worker

    shell = Worker()

    async def app(token):
        host, port = args.store.split(":")
        drt = await DistributedRuntime(
            store_host=host, store_port=int(port),
            advertise_host=args.advertise_host).connect()
        shell.add_runtime(drt)
        await run_prefill_worker(args, drt=drt, token=token)

    try:
        shell.execute(app)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
