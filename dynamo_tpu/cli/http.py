"""Standalone OpenAI HTTP frontend with live model discovery.

    python -m dynamo_tpu.cli.http --store 127.0.0.1:4222 --port 8080 \
        [--namespace dynamo] [--router-component router]

Watches the store's ``models/`` prefix: every registered model becomes a
served OpenAI model backed by a RemoteCoreEngine over the runtime data plane
(KV-routed when a router component is live). Models appear/disappear live as
workers register/die. Reference capability: components/http/src/main.rs +
lib/llm/src/http/service/discovery.rs.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json
import logging
from typing import Dict, Optional

from ..llm.http_service import HttpService, ModelManager, ServedModel
from ..llm.model_card import ModelDeploymentCard
from ..llm.pipeline import OpenAIChatEngine, OpenAICompletionEngine
from ..llm.remote import MODEL_PREFIX, RemoteCoreEngine, split_model_key
from ..runtime.component import Client, DistributedRuntime

log = logging.getLogger("dynamo_tpu.http")


class DiscoveryFrontend:
    def __init__(self, drt: DistributedRuntime, manager: ModelManager,
                 router_component: Optional[str] = None,
                 namespace: Optional[str] = None):
        self.drt = drt
        self.manager = manager
        self.router_component = router_component
        # configured namespace: the decisions-fetch fallback before any
        # model has registered (discovery would otherwise guess "dynamo")
        self.namespace = namespace
        self._clients: Dict[str, Client] = {}       # endpoint path -> client
        self._router_clients: Dict[str, Client] = {}
        self._decision_clients: Dict[str, Client] = {}  # ns -> audit client
        # (name, mtype) -> live registration store-keys. A model serves as
        # long as ANY registrant lives (replicas register under per-lease
        # keys; one replica dying must not unserve the others).
        self._registrations: Dict[tuple, set] = {}

    async def start(self) -> None:
        await self.drt.store.watch_prefix(MODEL_PREFIX, self._on_change)
        # initial snapshot
        for key, value in await self.drt.store.get_prefix(MODEL_PREFIX):
            await self._on_change(key, value, False)

    async def _client_for(self, endpoint_path: str) -> Client:
        if endpoint_path not in self._clients:
            ns, comp, ep = endpoint_path.split(".")
            cl = await self.drt.namespace(ns).component(comp) \
                .endpoint(ep).client().start()
            self._clients[endpoint_path] = cl
        return self._clients[endpoint_path]

    async def _router_for(self, ns: str) -> Optional[Client]:
        if not self.router_component:
            return None
        if ns not in self._router_clients:
            cl = await self.drt.namespace(ns) \
                .component(self.router_component).endpoint("route") \
                .client().start()
            self._router_clients[ns] = cl
        return self._router_clients[ns]

    async def fetch_router_decisions(self, limit: int = 0):
        """GET /v1/router/decisions backend: read the router's decision-
        audit ring over its ``decisions`` endpoint. Namespaces come from
        the models already discovered (falling back to the default
        namespace before any model registers). None = no live router; a
        LIVE router whose fetch fails raises, so the HTTP layer answers
        502 (router broken) instead of 404 (router absent)."""
        if not self.router_component:
            return None
        last_err: Optional[Exception] = None
        namespaces = (list(self._router_clients)
                      or [self.namespace or "dynamo"])
        for ns in namespaces:
            if ns not in self._decision_clients:
                self._decision_clients[ns] = await self.drt.namespace(ns) \
                    .component(self.router_component).endpoint("decisions") \
                    .client().start()
            cl = self._decision_clients[ns]
            if not cl.instances:
                continue
            try:
                async for resp in cl.generate({"limit": int(limit)}):
                    return resp.get("decisions", [])
            except Exception as e:  # noqa: BLE001 - surfaced as 502 below
                log.exception("router decisions fetch from %s failed", ns)
                last_err = e
        if last_err is not None:
            raise RuntimeError(f"live router failed the decisions fetch: "
                               f"{last_err}") from last_err
        return None

    async def _on_change(self, key: str, value: Optional[bytes],
                         deleted: bool) -> None:
        try:
            mt_name = split_model_key(key)
            if mt_name is None:
                return
            mtype, name = mt_name
            if deleted:
                regs = self._registrations.get((name, mtype))
                if regs is not None:
                    regs.discard(key)
                    if regs:
                        return      # surviving registrants keep serving
                    self._registrations.pop((name, mtype), None)
                served = self.manager.get(name)
                if served is not None:
                    if mtype == "chat":
                        served.chat_engine = None
                    else:
                        served.completion_engine = None
                    if (served.chat_engine is None
                            and served.completion_engine is None):
                        self.manager.remove(name)
                        log.info("model %s removed (no registrants left)",
                                 name)
                return
            d = json.loads(value.decode())
            card = ModelDeploymentCard.from_dict(d["card"])
            worker = await self._client_for(d["endpoint"])
            router = await self._router_for(d["endpoint"].split(".")[0])
            core = RemoteCoreEngine(worker, router, model_name=name)
            served = self.manager.get(name) or ServedModel(card)
            if mtype == "chat":
                served.chat_engine = OpenAIChatEngine(card, core)
            else:
                served.completion_engine = OpenAICompletionEngine(card, core)
            served.card = card
            self.manager.add(served)
            self._registrations.setdefault((name, mtype), set()).add(key)
            log.info("model %s (%s) -> %s", name, mtype, d["endpoint"])
        except Exception:
            log.exception("model discovery update failed for %s", key)


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-http")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--router-component", default=None,
                   help="component name of a KV router to consult")
    p.add_argument("--namespace", default=None,
                   help="scope the /metrics stage scrape to one namespace "
                        "(default: all namespaces in the store)")
    return p.parse_args(argv)


async def run_http(args, *, ready_event=None,
                   drt: Optional[DistributedRuntime] = None
                   ) -> HttpService:
    host, port = args.store.split(":")
    own = drt is None
    if own:
        drt = await DistributedRuntime(store_host=host,
                                       store_port=int(port)).connect()
    manager = ModelManager()
    frontend = DiscoveryFrontend(drt, manager, args.router_component,
                                 namespace=getattr(args, "namespace", None))
    await frontend.start()
    # store-wired service: /v1/traces stitches spans published by workers,
    # /metrics merges their per-stage histograms
    from ..utils.tracing import configure as configure_tracing
    configure_tracing(component="http")
    svc = HttpService(manager, host=args.host, port=args.port,
                      store=drt.store,
                      namespace=getattr(args, "namespace", None),
                      router_decisions=(frontend.fetch_router_decisions
                                        if args.router_component else None))
    # publish this frontend's stage dump (TTFT/ITL histograms recorded at
    # the streaming edge) plus its HTTP request counters to the store —
    # the planner's ttft_p90 signal, the SLO monitor's latency AND
    # availability objectives, and dyntop all read metrics_stage/; a
    # frontend that only *served* /metrics would keep those planes blind
    from ..llm.metrics_aggregator import StagePublisher

    svc.stage_worker_id = drt.worker_id   # /metrics skips our own dump
    pub_ns = getattr(args, "namespace", None) or "dynamo"

    # flight recorder + watchdog + incident coordination: the frontend's
    # rings hold the request-edge spans and its store-health transitions;
    # on a capture beacon it also contributes the router's live decision-
    # ring slice (the frontend already knows how to fetch it)
    from .. import obs

    obs_handle = await obs.start_process(
        "http", store=drt.store, namespace=pub_ns,
        proc_label=f"http:{drt.worker_id:x}")
    if args.router_component:
        obs_handle.manager.add_source("router_decisions",
                                      frontend.fetch_router_decisions)
    svc._obs_handle = obs_handle   # stopped by HttpService.stop()
    # fleet brownout level (utils/overload.py): watch the store key the
    # controller publishes so THIS frontend's admission gate applies the
    # active degradation level — the level is fleet state, not local state
    try:
        await svc.brownout.watch(drt.store, pub_ns)
    except Exception:
        log.warning("brownout watch failed; serving at level 0",
                    exc_info=True)

    # fleet plane (multi-model registry): /v1/models reports per-model
    # state, registered models' 404s get their own (bounded) metric label
    # so the planner can scale them from zero, and the per-tenant quota
    # table follows the registry's per-model tenant tables live
    from ..fleet.registry import FleetRegistry, fetch_fleet_status
    from ..utils.overload import tenant_quotas_from_env

    try:
        fleet_reg = await FleetRegistry(drt.store, pub_ns).start()
    except Exception:
        fleet_reg = None
        log.warning("fleet registry watch failed; serving without the "
                    "fleet view", exc_info=True)
    if fleet_reg is not None:
        svc.known_models = lambda: set(fleet_reg.models)

        async def fleet_status():
            status = await fetch_fleet_status(drt.store, pub_ns)
            for name, spec in fleet_reg.snapshot().items():
                # registered but never reconciled (no planner yet):
                # still listed, state honest about the blind spot
                status.setdefault(name, {"state": "unknown",
                                         "component": spec.component})
            return status

        svc.fleet_status = fleet_status
        env_quotas = tenant_quotas_from_env()

        def refresh_quotas(*_):
            table = dict(env_quotas)
            table.update(fleet_reg.tenant_quotas())
            svc.tenants.set_quotas(table)

        fleet_reg.on_change = refresh_quotas
        refresh_quotas()

    publisher = StagePublisher(drt.store, pub_ns, "http", drt.worker_id,
                               drt.lease)

    async def stage_publish_loop():
        while True:
            try:
                await publisher.publish(
                    extra_metrics=svc.registry.state_dump())
            except Exception:
                log.debug("frontend stage publish skipped", exc_info=True)
            await asyncio.sleep(2.0)

    svc._stage_pub_task = asyncio.create_task(stage_publish_loop())
    actual = await svc.start()
    print(f"dynamo_tpu http frontend on :{actual} (discovery mode)",
          flush=True)
    if ready_event is not None:
        ready_event.set()
    return svc


def main() -> None:
    from ..utils.logging_ext import init_logging
    init_logging()

    async def amain():
        args = parse_args()
        await run_http(args)
        while True:
            await asyncio.sleep(3600)

    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
