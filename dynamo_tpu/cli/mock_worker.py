"""Mock worker: publishes fake ForwardPassMetrics under a live lease.

    python -m dynamo_tpu.cli.mock_worker --namespace dynamo \
        --component backend --store localhost:4222 [--period 1.0]

Lets the metrics aggregator, router scoring and dashboards be exercised with
no engine at all: the snapshot values ramp deterministically so scrapes can
be asserted against. Reference capability:
components/metrics/src/bin/mock_worker.rs.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json
import logging
from typing import Optional

from ..llm.kv_router.protocols import ForwardPassMetrics
from ..llm.metrics_aggregator import metrics_key
from ..runtime.component import DistributedRuntime

log = logging.getLogger("dynamo_tpu.mock_worker")


def snapshot(tick: int, total_slots: int, kv_total: int) -> ForwardPassMetrics:
    """Deterministic ramp: active load cycles 0..total, kv follows."""
    active = tick % (total_slots + 1)
    kv_active = (tick * 7) % (kv_total + 1)
    return ForwardPassMetrics(
        request_active_slots=float(active),
        request_total_slots=float(total_slots),
        kv_active_blocks=float(kv_active),
        kv_total_blocks=float(kv_total),
        num_requests_waiting=float(tick % 3),
        gpu_cache_usage_perc=kv_active / kv_total if kv_total else 0.0,
        gpu_prefix_cache_hit_rate=0.5,
    )


async def run_mock_worker(args, *, drt: Optional[DistributedRuntime] = None,
                          ready_event: Optional[asyncio.Event] = None) -> None:
    host, port = args.store.split(":")
    own = drt is None
    if own:
        drt = await DistributedRuntime(store_host=host,
                                       store_port=int(port)).connect()
    key = metrics_key(args.namespace, args.component, drt.worker_id)
    tick = 0
    print(f"mock worker {drt.worker_id:x} publishing {key}", flush=True)
    try:
        while True:
            m = snapshot(tick, args.total_slots, args.kv_total)
            await drt.store.put(key, json.dumps(m.to_dict()).encode(),
                                lease=drt.lease)
            if ready_event is not None and tick == 0:
                ready_event.set()
            tick += 1
            await asyncio.sleep(args.period)
    finally:
        if own:
            await drt.close()


def main(argv=None) -> None:
    ap = EnvDefaultsParser("dynamo-mock-worker")
    ap.add_argument("--store", default="127.0.0.1:4222")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="backend")
    ap.add_argument("--period", type=float, default=1.0)
    ap.add_argument("--total-slots", type=int, default=8)
    ap.add_argument("--kv-total", type=int, default=512)
    args = ap.parse_args(argv)
    from ..utils.logging_ext import init_logging
    init_logging()
    asyncio.run(run_mock_worker(args))


if __name__ == "__main__":
    main()
