"""Planner binary: the closed-loop prefill/decode autoscaler daemon.

    python -m dynamo_tpu.cli.planner --store 127.0.0.1:4222 \
        --namespace dynamo --decode-component backend \
        [--prefill-component prefill] \
        --policy load|sla --connector local|kube|none \
        [--dry-run] [--profile profile.json --ttft-target 2.0 \
         --itl-target 0.05] [--min-replicas 1 --max-replicas 8]

Every flag resolves its default through ``DYN_PLANNER_<FLAG>`` (the
EnvDefaultsParser layering), so the whole knob surface is env-drivable:
``DYN_PLANNER_DRY_RUN=1``, ``DYN_PLANNER_MAX_REPLICAS=16``, ...

Inspect and steer the running loop with ``python -m
dynamo_tpu.cli.plannerctl`` (status / decisions / override / pause).
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from ..planner.connectors import (KubeConnector, LocalConnector,
                                  NullConnector, PoolSpec)
from ..planner.loop import Planner, PlannerConfig
from ..planner.policy import LoadPolicy, SlaPolicy
from ..planner.profile import ProfileTable
from ..runtime.component import DistributedRuntime
from ..utils import tracing
from ..utils.dynconfig import EnvDefaultsParser

log = logging.getLogger("dynamo_tpu.planner")


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dynamo-planner")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--decode-component", default="backend")
    p.add_argument("--prefill-component", default="",
                   help="component of the prefill pool ('' = decode only)")
    p.add_argument("--policy", choices=("load", "sla"), default="load")
    p.add_argument("--connector", choices=("local", "kube", "none"),
                   default="none")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--cooldown-up", type=float, default=30.0)
    p.add_argument("--cooldown-down", type=float, default=120.0)
    p.add_argument("--down-consensus", type=int, default=3)
    p.add_argument("--dry-run", action="store_true",
                   help="publish decisions but never actuate")
    p.add_argument("--fleet", action="store_true",
                   help="reconcile the multi-model fleet registry "
                        "(fleet_models/): pool set follows `ctl fleet "
                        "add/remove` live, targets pass through the "
                        "chip arbiter under --total-chips, per-model "
                        "status published to fleet_status/")
    p.add_argument("--brownout", action="store_true",
                   help="run the SLO-burn brownout controller on this "
                        "loop (publishes the fleet degradation level; "
                        "DYN_BROWNOUT_* knobs)")
    # load policy knobs
    p.add_argument("--queue-high", type=float, default=1.0)
    p.add_argument("--occupancy-high", type=float, default=0.85)
    p.add_argument("--occupancy-low", type=float, default=0.3)
    p.add_argument("--kv-high", type=float, default=0.9)
    p.add_argument("--kv-low", type=float, default=0.5)
    # sla policy knobs
    p.add_argument("--profile", default=None,
                   help="profile table JSON (planner.profile sweep output)")
    p.add_argument("--ttft-target", type=float, default=2.0)
    p.add_argument("--itl-target", type=float, default=0.05)
    # local connector knobs
    p.add_argument("--worker-engine", default="jax",
                   help="--engine for spawned workers (jax|echo)")
    p.add_argument("--worker-chips", type=int, default=0,
                   help="TPU chips per spawned decode worker")
    p.add_argument("--prefill-worker-chips", type=int, default=0)
    p.add_argument("--total-chips", type=int, default=4)
    p.add_argument("--platform", default="cpu", choices=("cpu", "tpu"))
    p.add_argument("--worker-args", default="",
                   help="extra args appended to spawned workers, "
                        "space-separated")
    # kube connector knobs
    p.add_argument("--kube-url", default=None,
                   help="apiserver base URL ('' = from kubeconfig)")
    p.add_argument("--kube-token", default=None)
    p.add_argument("--kube-insecure", action="store_true")
    p.add_argument("--kube-namespace", default="default")
    p.add_argument("--kube-deployment", default=None,
                   help="DynamoDeployment name to patch")
    p.add_argument("--kube-mode", choices=("crd", "deployment"),
                   default="crd")
    return p.parse_args(argv)


def build_policy(args):
    if args.policy == "sla":
        if not args.profile:
            raise SystemExit("--policy sla requires --profile (run "
                             "python -m dynamo_tpu.planner.profile first)")
        table = ProfileTable.load(args.profile)
        return SlaPolicy(table, ttft_target=args.ttft_target,
                         itl_target=args.itl_target)
    return LoadPolicy(queue_high=args.queue_high,
                      occupancy_high=args.occupancy_high,
                      occupancy_low=args.occupancy_low,
                      kv_high=args.kv_high, kv_low=args.kv_low)


def build_connector(args, pools):
    if args.connector == "local":
        extra = [a for a in args.worker_args.split() if a]
        specs = {}
        for pool, component in pools.items():
            if pool == "prefill":
                specs[pool] = PoolSpec(
                    component=component, chips=args.prefill_worker_chips,
                    module="dynamo_tpu.cli.prefill_worker",
                    extra_args=["--decode-component",
                                args.decode_component, *extra])
            else:
                specs[pool] = PoolSpec(component=component,
                                       chips=args.worker_chips,
                                       engine=args.worker_engine,
                                       extra_args=list(extra))
        return LocalConnector(args.store, args.namespace, specs,
                              total_chips=args.total_chips,
                              platform=args.platform)
    if args.connector == "kube":
        if not args.kube_deployment:
            raise SystemExit("--connector kube requires --kube-deployment")
        from ..deploy.rest_api import RestKubeApi

        if args.kube_url:
            api = RestKubeApi(args.kube_url, token=args.kube_token,
                              insecure_skip_verify=args.kube_insecure)
        else:
            api = RestKubeApi.from_kubeconfig()
        return KubeConnector(api, args.kube_deployment,
                             kube_namespace=args.kube_namespace,
                             mode=args.kube_mode,
                             service_for_pool=dict(pools))
    return NullConnector()


async def run_planner(args, *, ready_event=None, drt=None) -> None:
    # getattr: harnesses build the Namespace by hand (chaos/soak rigs)
    fleet_mode = getattr(args, "fleet", False)
    if fleet_mode:
        # fleet mode: the pool set comes from the model registry, live —
        # starting empty is normal (models `ctl fleet add`-ed later join
        # on the next tick)
        pools = {}
    else:
        pools = {"decode": args.decode_component}
        if args.prefill_component:
            pools["prefill"] = args.prefill_component
    own_drt = drt is None
    if own_drt:
        host, port = args.store.split(":")
        drt = await DistributedRuntime(store_host=host,
                                       store_port=int(port)).connect()
    tracing.configure(component="planner")
    span_sink = await tracing.StoreSpanSink(drt.store).start()
    policy = build_policy(args)
    connector = build_connector(args, pools)
    cfg = PlannerConfig(
        interval=args.interval, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, cooldown_up=args.cooldown_up,
        cooldown_down=args.cooldown_down,
        down_consensus=args.down_consensus, dry_run=args.dry_run,
        brownout=args.brownout)
    fleet = None
    if fleet_mode:
        from ..fleet import FleetPlane

        fleet = FleetPlane(drt.store, args.namespace,
                           total_chips=args.total_chips)
    planner = await Planner(drt, args.namespace, pools, policy, connector,
                            cfg, fleet=fleet).start()
    mode = "DRY-RUN" if args.dry_run else "live"
    log.info("planner %s: pools=%s policy=%s connector=%s fleet=%s", mode,
             pools, policy.name, connector.name, bool(fleet))
    print(f"planner serving ({mode}, policy={policy.name}, "
          f"connector={connector.name}, "
          f"pools={'<fleet registry>' if fleet else pools})", flush=True)
    if ready_event is not None:
        ready_event.set()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await planner.stop()
        try:
            await span_sink.stop()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        if own_drt:
            await drt.close()


def main() -> None:
    from ..utils.logging_ext import init_logging

    init_logging()
    args = parse_args()
    try:
        asyncio.run(run_planner(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
