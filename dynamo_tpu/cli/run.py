"""Single-binary style launcher: ``python -m dynamo_tpu.cli.run in=... out=...``

Input modes:  http | text | stdin | batch:<file.jsonl> | none
Output modes: echo_core | echo_full | jax | pystr:<file.py> |
pytok:<file.py> | dyn://<ns.component.endpoint>

Reference capability: launch/dynamo-run (lib.rs:53-456, opt.rs, flags.rs,
input/{http,text,batch}.rs) — the in=X out=Y matrix, model flags, and the
built-in batch load generator.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

from ..llm.http_service import HttpService, ModelManager, ServedModel
from ..llm.model_card import ModelDeploymentCard
from ..llm.pipeline import build_chat_engine, build_completion_engine
from ..llm.protocols.openai import (
    ChatCompletionRequest,
    aggregate_chat_chunks,
)
from ..runtime.engine import AsyncEngine, Context


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dynamo-run")
    p.add_argument("positional", nargs="*",
                   help="in=<mode> out=<engine> (order-free)")
    p.add_argument("--model-path", default=None)
    p.add_argument("--model-name", default=None)
    p.add_argument("--http-port", type=int, default=8080)
    p.add_argument("--http-host", default="0.0.0.0")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--kv-block-size", type=int, default=64)
    p.add_argument("--context-length", type=int, default=None)
    p.add_argument("--max-tokens", type=int, default=128,
                   help="default max tokens for text/batch modes")
    p.add_argument("--concurrency", type=int, default=8,
                   help="batch mode concurrency")
    p.add_argument("--extra-engine-args", default=None,
                   help="extra engine kwargs: a JSON file path, or inline "
                        "JSON if the value starts with '{'")
    p.add_argument("--store", default="127.0.0.1:4222",
                   help="dynstore host:port (out=dyn:// remote mode)")
    p.add_argument("--connect-timeout", type=float, default=30.0,
                   help="seconds to wait for a live out=dyn:// instance")
    args = p.parse_args(argv)
    args.input, args.output = "text", "echo_core"
    for tok in args.positional:
        if tok.startswith("in="):
            args.input = tok[3:]
        elif tok.startswith("out="):
            args.output = tok[4:]
        else:
            p.error(f"unrecognized argument {tok!r}")
    return args


def make_card(args) -> ModelDeploymentCard:
    if args.model_path:
        card = ModelDeploymentCard.resolve(args.model_path, args.model_name)
    else:
        card = ModelDeploymentCard.synthetic(args.model_name or args.output)
    if args.context_length:
        card.context_length = args.context_length
    card.kv_block_size = args.kv_block_size
    return card


def make_engines(args, card: ModelDeploymentCard):
    """Returns (chat_engine, completion_engine) at the OpenAI level."""
    out = args.output
    if out in ("echo_core", "echo_full"):
        return (build_chat_engine(card, out), build_completion_engine(card, out))
    if out == "jax":
        try:
            from ..engine.engine import JaxEngine, JaxEngineConfig
        except ImportError as e:
            raise SystemExit(f"out=jax engine unavailable: {e}")

        extra: Dict[str, Any] = {}
        if args.extra_engine_args:
            if args.extra_engine_args.lstrip().startswith("{"):
                extra = json.loads(args.extra_engine_args)
            else:
                with open(args.extra_engine_args) as f:
                    extra = json.load(f)
        cfg = JaxEngineConfig.from_card(
            card, tensor_parallel=args.tensor_parallel_size, **extra)
        core = JaxEngine(cfg)
        return (build_chat_engine(card, "core", core),
                build_completion_engine(card, "core", core))
    if out.startswith(("pystr:", "pytok:")):
        from ..llm.python_engine import PythonEngineError, build_python_engines

        try:
            return build_python_engines(out, card)
        except PythonEngineError as e:
            raise SystemExit(str(e))
    if out.startswith("dyn://"):
        # async connect: handled by connect_remote_engines in amain
        raise AssertionError("dyn:// handled before make_engines")
    raise SystemExit(f"unknown out={out}")


async def connect_remote_engines(args, card: ModelDeploymentCard):
    """``out=dyn://ns.component.endpoint`` — drive a REMOTE worker's core
    engine over the runtime data plane (ref dynamo-run's remote client
    mode, launch/dynamo-run/src/lib.rs in=..., out=dyn://)."""
    from ..llm.remote import RemoteCoreEngine
    from ..runtime.component import DistributedRuntime

    path = args.output[len("dyn://"):]
    parts = path.split(".")
    if len(parts) != 3:
        raise SystemExit(f"out=dyn://{path}: expected ns.component.endpoint")
    host, _, port = args.store.partition(":")
    drt = await DistributedRuntime(store_host=host or "127.0.0.1",
                                   store_port=int(port or 4222)).connect()
    client = await (drt.namespace(parts[0]).component(parts[1])
                    .endpoint(parts[2]).client().start())
    try:
        await client.wait_for_instances(1, timeout=args.connect_timeout)
    except TimeoutError as e:
        raise SystemExit(f"out={args.output}: {e}")
    core = RemoteCoreEngine(client)
    return (build_chat_engine(card, "core", core),
            build_completion_engine(card, "core", core))


# ---------------------------------------------------------------------------
# input modes
# ---------------------------------------------------------------------------

async def run_http(args, card, chat_engine, completion_engine) -> None:
    from ..utils.tracing import configure as configure_tracing

    configure_tracing(component="http")
    manager = ModelManager()
    manager.add(ServedModel(card, chat_engine, completion_engine))
    svc = HttpService(manager, host=args.http_host, port=args.http_port)
    port = await svc.start()
    print(f"dynamo_tpu http frontend listening on :{port} "
          f"(model={card.name}, out={args.output})", flush=True)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await svc.stop()


async def _ask(chat_engine: AsyncEngine, card, prompt: str, max_tokens: int,
               stream_out=True) -> str:
    req = ChatCompletionRequest.from_dict({
        "model": card.name,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
    })
    parts: List[str] = []
    async for ch in chat_engine.generate(req, Context()):
        if "event" in ch:
            continue
        delta = ch["choices"][0].get("delta", {})
        if delta.get("content"):
            parts.append(delta["content"])
            if stream_out:
                print(delta["content"], end="", flush=True)
    if stream_out:
        print()
    return "".join(parts)


async def run_text(args, card, chat_engine, _completion_engine) -> None:
    print(f"dynamo_tpu interactive ({card.name}). Ctrl-D to exit.")
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, lambda: input("> "))
        except EOFError:
            return
        if line.strip():
            await _ask(chat_engine, card, line, args.max_tokens)


async def run_stdin(args, card, chat_engine, _c) -> None:
    data = sys.stdin.read()
    if data.strip():
        await _ask(chat_engine, card, data, args.max_tokens)


async def run_batch(args, card, chat_engine, _c, path: str) -> Dict[str, Any]:
    """JSONL load generator: one {"text": ...} (or {"prompt": ...}) per line.
    Reports latency/throughput stats (the built-in perf harness)."""
    prompts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                d = json.loads(line)
                prompts.append(d.get("text") or d.get("prompt") or "")
    sem = asyncio.Semaphore(args.concurrency)
    latencies: List[float] = []
    ttfts: List[float] = []
    tokens_out = 0

    async def one(prompt: str):
        nonlocal tokens_out
        async with sem:
            t0 = time.monotonic()
            first: Optional[float] = None
            req = ChatCompletionRequest.from_dict({
                "model": card.name,
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": args.max_tokens,
            })
            async for ch in chat_engine.generate(req, Context()):
                if "event" in ch:
                    continue
                if first is None:
                    first = time.monotonic() - t0
                u = ch.get("usage")
                if u:
                    tokens_out += u["completion_tokens"]
            latencies.append(time.monotonic() - t0)
            ttfts.append(first if first is not None else 0.0)

    t_start = time.monotonic()
    await asyncio.gather(*(one(p) for p in prompts))
    wall = time.monotonic() - t_start
    stats = {
        "requests": len(prompts),
        "wall_s": round(wall, 3),
        "req_per_s": round(len(prompts) / wall, 2) if wall else None,
        "tokens_out": tokens_out,
        "tok_per_s": round(tokens_out / wall, 1) if wall else None,
        "p50_latency_s": round(statistics.median(latencies), 4) if latencies else None,
        "p50_ttft_s": round(statistics.median(ttfts), 4) if ttfts else None,
        "p99_latency_s": round(sorted(latencies)[int(0.99 * (len(latencies) - 1))], 4)
        if latencies else None,
    }
    print(json.dumps(stats), flush=True)
    return stats


from ..utils.hostmesh import honor_jax_platforms_env as \
    _honor_jax_platforms_env  # one home for the axon-plugin workaround


async def amain(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    _honor_jax_platforms_env()
    card = make_card(args)
    if args.output.startswith("dyn://"):
        chat_engine, completion_engine = await connect_remote_engines(args,
                                                                      card)
    else:
        chat_engine, completion_engine = make_engines(args, card)
    mode = args.input
    if mode == "http":
        await run_http(args, card, chat_engine, completion_engine)
    elif mode == "text":
        await run_text(args, card, chat_engine, completion_engine)
    elif mode == "stdin":
        await run_stdin(args, card, chat_engine, completion_engine)
    elif mode.startswith("batch:"):
        await run_batch(args, card, chat_engine, completion_engine,
                        mode.split(":", 1)[1])
    elif mode == "none":
        print("engine initialized; no input mode (in=none)")
    else:
        raise SystemExit(f"unknown in={mode}")


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
