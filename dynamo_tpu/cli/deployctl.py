"""deployctl — kubectl-style CLI for Deployment resources.

    python -m dynamo_tpu.cli.deployctl apply -f dep.yaml [--store h:p]
    python -m dynamo_tpu.cli.deployctl list
    python -m dynamo_tpu.cli.deployctl status <namespace>/<name>
    python -m dynamo_tpu.cli.deployctl delete <namespace>/<name>
    python -m dynamo_tpu.cli.deployctl render -f dep.yaml [--image IMG]
    python -m dynamo_tpu.cli.deployctl push <name> <bundle> [--api URL]
    python -m dynamo_tpu.cli.deployctl operator [--resync S]

``render`` emits Kubernetes manifests for the resource; ``operator`` runs
the local reconciling operator in the foreground.

Reference capability: the dynamo deploy/deployment CLI group
(deploy/dynamo/sdk/cli/deployment.py) + kubectl against the operator CRDs.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..deploy.crd import DEPLOY_PREFIX, Deployment
from ..runtime.scale.shards import make_store_client
from ..runtime.store_client import StoreClient


def _load_resource(path: str) -> Deployment:
    import yaml

    with open(path) as f:
        return Deployment.from_dict(yaml.safe_load(f))


async def _with_client(store: str, fn):
    host, port = store.split(":")
    client = await make_store_client(host, int(port)).connect()
    try:
        return await fn(client)
    finally:
        await client.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("deployctl")
    ap.add_argument("--store", default="127.0.0.1:4222")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("apply")
    p.add_argument("-f", "--file", required=True)
    sub.add_parser("list")
    p = sub.add_parser("status")
    p.add_argument("target")
    p = sub.add_parser("delete")
    p.add_argument("target")
    p = sub.add_parser("render")
    p.add_argument("-f", "--file", required=True)
    p.add_argument("--image", default="dynamo-tpu:latest")
    p.add_argument("--no-store", action="store_true")
    p = sub.add_parser("push")
    p.add_argument("name")
    p.add_argument("bundle", help="tarball or single-module .py file")
    p.add_argument("--api", default="http://127.0.0.1:8082",
                   help="api-store base URL")
    p = sub.add_parser("build")
    p.add_argument("path", help="graph module .py or package directory")
    p.add_argument("--tag", default="dynamo-tpu-graph:latest")
    p.add_argument("--base", default="dynamo-tpu:latest")
    p.add_argument("--out", default=None,
                   help="write the OCI build context tar here "
                        "(default <name>-context.tar)")
    p.add_argument("--builder", default=None,
                   help="image builder command to run on the context, "
                        "e.g. 'docker build' or 'buildctl ...'")
    p = sub.add_parser("operator")
    p.add_argument("--resync", type=float, default=5.0)
    p.add_argument("--platform", default="cpu")
    args = ap.parse_args(argv)

    if args.cmd == "apply":
        dep = _load_resource(args.file)

        async def do(client):
            from ..deploy.operator import apply

            await apply(client, dep)
            print(f"applied {dep.key()} (generation {dep.generation})")

        asyncio.run(_with_client(args.store, do))
        return 0

    if args.cmd == "list":
        async def do(client):
            for key, raw in await client.get_prefix(DEPLOY_PREFIX):
                try:
                    d = Deployment.from_bytes(raw)
                except ValueError:
                    continue
                print(f"{d.key()}  graph={d.spec.graph} "
                      f"generation={d.generation}")

        asyncio.run(_with_client(args.store, do))
        return 0

    if args.cmd in ("status", "delete"):
        ns, _, name = args.target.partition("/")
        if not name:
            ns, name = "default", ns

        async def do(client):
            from ..deploy.operator import delete, get_status

            if args.cmd == "delete":
                ok = await delete(client, ns, name)
                print("deleted" if ok else "not found")
                return 0 if ok else 1
            st = await get_status(client, ns, name)
            if st is None:
                print("no status recorded")
                return 1
            print(json.dumps(st.to_dict(), indent=2))
            return 0

        return asyncio.run(_with_client(args.store, do)) or 0

    if args.cmd == "push":
        async def push():
            import aiohttp

            with open(args.bundle, "rb") as f:
                data = f.read()
            async with aiohttp.ClientSession() as s:
                r = await s.post(
                    f"{args.api}/api/v1/artifacts/{args.name}/versions",
                    data=data)
                if r.status != 201:
                    # error bodies may be plain text (HTTPBadRequest)
                    print(f"push failed ({r.status}): {await r.text()}")
                    return 1
                body = await r.json()
                print(f"pushed {args.name} v{body['version']} "
                      f"({body['size']} bytes, sha256 {body['sha256'][:12]}) "
                      f"-> deploy with graph: "
                      f"\"artifact://{args.name}#<module>:<Class>\"")
                return 0

        return asyncio.run(push())

    if args.cmd == "render":
        dep = _load_resource(args.file)
        from ..deploy.manifests import render_manifests, to_yaml
        from ..deploy.operator import Operator

        services = Operator._resolve_graph(dep)
        print(to_yaml(render_manifests(
            dep, services, image=args.image,
            include_store=not args.no_store)))
        return 0

    if args.cmd == "build":
        from ..deploy.imagebuild import build_context, run_builder

        ctx = build_context(args.path, base_image=args.base,
                            out_path=args.out)
        print(f"build context: {ctx} (Dockerfile + graph bundle)")
        if args.builder:
            rc = run_builder(args.builder, ctx, args.tag)
            print(f"builder exited {rc}")
            return rc
        print(f"no --builder given; build with e.g.\n"
              f"  docker build -t {args.tag} - < {ctx}")
        return 0

    if args.cmd == "operator":
        from ..deploy.operator import LocalRunner, Operator

        host, port = args.store.split(":")

        async def run():
            op = Operator(host, int(port),
                          runner=LocalRunner(args.store, args.platform),
                          resync_interval=args.resync)
            await op.start()
            print(f"operator watching {DEPLOY_PREFIX} on {args.store}",
                  flush=True)
            try:
                while True:
                    await asyncio.sleep(3600)
            finally:
                await op.close()

        asyncio.run(run())
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
