"""Worker binary: serve an engine as a distributed endpoint.

    python -m dynamo_tpu.cli.worker --engine jax|echo --namespace dynamo \
        --component backend --store localhost:4222 [--model-path ...] \
        [--register-model NAME]

Serves ``generate`` (BackendInput -> EngineOutput stream), publishes KV cache
events on the component event plane, and refreshes ForwardPassMetrics in the
store under its lease (the aggregator scrapes the prefix). This is the
equivalent of a reference engine worker process: serve_endpoint + KV event
publisher + metrics publisher.
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import json
import logging
import time
from typing import Optional

from ..llm.disagg import (DisaggConfig, DisaggRouter, PrefillQueue,
                          RemotePrefillRequest)
from ..llm.kv_router.protocols import KV_EVENT_SUBJECT, ForwardPassMetrics
from ..llm.kv_router.publisher import KvEventPublisher
from ..llm.kv_transfer import (KV_RECEIVE_ENDPOINT, KvReceiver,
                               RemotePrefillError, stream_enabled)
from ..llm.model_card import ModelDeploymentCard
from ..llm.protocols.common import BackendInput
from ..llm.remote import register_model, serve_core_engine
from ..runtime.component import DistributedRuntime
from ..runtime.store_client import StoreError
from ..utils import overload, tracing

log = logging.getLogger("dynamo_tpu.worker")

from ..llm.metrics_aggregator import METRICS_PREFIX, metrics_key  # noqa: E402
# (canonical definitions live with the aggregator; re-exported here for
# backward compatibility with existing imports)


def run_follower(args) -> None:
    """Follower node (rank > 0) of a multi-host worker: join the global
    mesh via jax.distributed, build the identical engine core, then replay
    the leader's dispatch stream forever. No endpoint, no registration —
    the multi-host slice is ONE logical worker published by the leader."""
    from ..engine.engine import EngineCore
    from ..parallel.multihost import FollowerLoop, init_distributed

    init_distributed(args.coordinator, args.num_nodes, args.node_rank)
    cfg = _engine_cfg(args)
    core = EngineCore(cfg)
    leader_host = args.coordinator.split(":")[0]
    print(f"follower {args.node_rank}/{args.num_nodes} joined mesh; "
          f"replaying dispatches from {leader_host}:{args.dispatch_port}",
          flush=True)
    FollowerLoop(core, leader_host, args.dispatch_port).run()


def _build_card(args) -> ModelDeploymentCard:
    if args.model_path:
        card = ModelDeploymentCard.resolve(args.model_path, args.model_name)
    else:
        card = ModelDeploymentCard.synthetic(args.model_name or "echo")
    card.kv_block_size = args.kv_block_size
    return card


def _engine_cfg(args, card: Optional[ModelDeploymentCard] = None):
    from ..engine.engine import JaxEngineConfig

    if card is None:
        card = _build_card(args)
    extra = json.loads(args.extra_engine_args) if args.extra_engine_args else {}
    if getattr(args, "num_nodes", 1) > 1:
        # multi-host lockstep covers exactly the dispatch-hooked programs:
        # host-tier restores / disagg injection are per-leader device ops
        # and must stay off
        extra["enable_prefix_reuse"] = False
        extra["host_cache_blocks"] = 0
        extra["disk_cache_blocks"] = 0
    from ..llm import kv_cluster

    if kv_cluster.enabled():
        # cluster sharing needs sealed blocks mirrored to the host tier
        # (write-through) so peers can fetch prefixes that never saw
        # device eviction pressure; a no-op when host_cache_blocks=0
        extra.setdefault("cluster_writethrough", True)
    return JaxEngineConfig.from_card(card, tensor_parallel=args.tp, **extra)


async def _connect_drt(args) -> DistributedRuntime:
    host, port = args.store.split(":")
    return await DistributedRuntime(
        store_host=host, store_port=int(port),
        advertise_host=args.advertise_host).connect()


async def run_worker(args, *, ready_event: Optional[asyncio.Event] = None,
                     drt: Optional[DistributedRuntime] = None,
                     token=None) -> None:
    multihost = getattr(args, "num_nodes", 1) > 1
    publisher = None
    if multihost:
        if args.engine != "jax":
            raise SystemExit("--num-nodes > 1 requires --engine jax")
        if getattr(args, "enable_disagg", False):
            raise SystemExit("--enable-disagg is not supported with "
                             "--num-nodes > 1 yet")
        from ..parallel.multihost import DispatchPublisher, init_distributed

        init_distributed(args.coordinator, args.num_nodes, args.node_rank)
        publisher = DispatchPublisher(args.dispatch_port, args.num_nodes - 1)
    own_drt = drt is None
    if own_drt:
        drt = await _connect_drt(args)
    if token is not None:
        # reference semantics (etcd.rs:55-76): losing the liveness lease
        # cancels the worker — shut down cleanly so the orchestrator
        # restarts us with a fresh lease, instead of serving unroutably.
        # With store reconnect this is now the LAST resort: transient
        # connection loss re-establishes the session (lease re-granted
        # under the same id, endpoint keys re-put) and the worker keeps
        # serving; the callback fires only when the reconnect window is
        # exhausted or the server could not preserve our identity.
        def _lease_lost(lease: int) -> None:
            log.critical("liveness lease %x unrecoverably lost; "
                         "shutting down", lease)
            token.cancel()
        drt.store.on_lease_lost = _lease_lost

    def _session_replayed() -> None:
        log.warning("store session re-established: lease %x re-granted, "
                    "endpoint/model keys re-registered", drt.worker_id)
    drt.store.on_session_replayed = _session_replayed
    ns = drt.namespace(args.namespace)
    component = ns.component(args.component)

    # tracing: span context arrives over the wire (rpc spans) and via the
    # prefill queue; finished spans flush to the store so the frontend's
    # /v1/traces endpoint can stitch the cross-process timeline
    tracing.configure(component="decode_worker")
    span_sink = await tracing.StoreSpanSink(drt.store).start()

    # flight recorder + hang watchdog + incident coordination: the rings
    # mirror every finished span (head-sampled-out ones included), the
    # watchdog turns wedged decode dispatches / transfers / drains into
    # stall:* spans, and any cluster beacon freezes our rings into the
    # coordinated bundle. SIGUSR2 = manual capture (real process only).
    from .. import obs

    obs_handle = await obs.start_process(
        "decode_worker", store=drt.store, namespace=args.namespace,
        proc_label=f"decode_worker:{drt.worker_id:x}",
        span_sink=span_sink, install_signal=token is not None)

    # --- engine -------------------------------------------------------
    card = _build_card(args)

    core = None
    if args.engine == "jax":
        from ..engine.engine import JaxEngine

        cfg = _engine_cfg(args, card)
        # engine bring-up (jax init, weight load, device_put) can exceed the
        # lease TTL — run it off-loop so lease keepalives keep flowing
        engine = await asyncio.get_running_loop().run_in_executor(
            None, lambda: JaxEngine(cfg))
        core = engine.core
        if publisher is not None:
            # every follower must see the dispatch stream from the first
            # dispatch: block until the full slice has joined
            await asyncio.get_running_loop().run_in_executor(
                None, publisher.wait_for_followers)
            core.dispatch_hook = publisher.hook
            print(f"multi-host leader: {args.num_nodes - 1} followers "
                  f"in lockstep", flush=True)
    else:
        from ..llm.engines import EchoCoreEngine

        engine = EchoCoreEngine()

    # --- KV event publishing -----------------------------------------
    async def publish(subject, payload):
        await component.publish(subject, payload)

    pub = KvEventPublisher(worker_id=drt.worker_id, publish=publish,
                           subject=KV_EVENT_SUBJECT)
    await pub.start()
    if core is not None:
        core.pool.on_block_sealed = pub.block_stored
        core.pool.on_blocks_removed = pub.blocks_removed

    # --- cluster KV sharing (DYN_KV_CLUSTER=1) -----------------------
    # serve the kv_fetch donor endpoint over the host tier, publish this
    # worker's sealed-block registry record (lease-bound), and prefetch
    # donor-stamped prefixes before requests enter the engine
    from ..llm import kv_cluster

    cluster = None
    if core is not None and kv_cluster.enabled():
        cluster = await kv_cluster.KvClusterWorker.attach(
            component, drt, args.namespace, core)

    # --- serve endpoint ----------------------------------------------
    # worker-ingress overload gate (DYN_WORKER_SLOTS / DYN_WORKER_QUEUE_
    # DEPTH, unset = off): bounded, priority-ordered slot queue with
    # predictive shedding — excess load fails in milliseconds as a typed
    # 429 naming this stage instead of queueing into a deadline burn
    gate = overload.gate_from_env()
    endpoint = component.endpoint("generate")
    engine_ref = None         # set on the simple path (model mobility)
    served = None
    if getattr(args, "enable_disagg", False) and core is not None:
        # decode worker with conditional remote prefill (SURVEY §3.2):
        # long cold prompts go to the shared queue; KV comes back on the
        # kv_receive endpoint and the request enters decode directly
        queue = PrefillQueue(drt.store, args.namespace)
        drouter = await DisaggRouter(
            args.namespace,
            config=DisaggConfig(
                max_local_prefill_length=getattr(
                    args, "max_local_prefill_length", 1000),
                max_prefill_queue_size=getattr(
                    args, "max_prefill_queue_size", 2)),
        ).start(drt.store)
        receiver = KvReceiver(worker_id=drt.worker_id)
        await component.endpoint(KV_RECEIVE_ENDPOINT).serve(receiver.handler)

        remote_timeout = getattr(args, "remote_prefill_timeout", 120.0)

        from ..llm.kv_transfer import await_remote_kv as _await_kv

        async def await_remote_kv(ctx, fut):
            return await _await_kv(ctx, fut, queue, receiver,
                                   remote_timeout)

        async def generate_handler(request, ctx):
            bi = BackendInput.from_dict(request)
            if cluster is not None:
                # donor-stamped prefix fetch BEFORE the slot gate and the
                # local probe: the peer fetch overlaps the queue wait
                # instead of holding a bounded slot through up to the
                # fetch timeout of network I/O (same invariant as the
                # non-disagg path's prefetch-outside-the-gate wrap), and
                # the deposited blocks count as local prefix hits, so a
                # cluster-warm prompt prefills locally instead of paying
                # the remote-prefill queue for KV a peer already holds
                await cluster.fetcher.ensure_prefix(bi, ctx)
            if hasattr(engine, "prefetch_tiers"):
                # placement-driven h2d prefetch: the upload of matched
                # local tier blocks runs on an executor thread WHILE this
                # request waits at the slot gate below, so admission's
                # restore is a d2d scatter, not a critical-path h2d
                from ..utils.aiotasks import spawn_blocking
                spawn_blocking(engine.prefetch_tiers, bi,
                               name="h2d-prefetch")
            if gate is not None:
                await gate.acquire(ctx.priority, ctx.deadline)
                svc_started = time.monotonic()
                try:
                    async for item in _generate_disagg(bi, request, ctx):
                        yield item
                finally:
                    gate.release(time.monotonic() - svc_started)
            else:
                async for item in _generate_disagg(bi, request, ctx):
                    yield item

        async def _generate_disagg(bi, request, ctx):
            # local prefix-cache hits count against remoting: a prompt we
            # mostly have cached prefills locally regardless of length.
            # CROSS-THREAD CONTRACT: this runs on the asyncio thread while
            # the engine thread mutates the block pool. probe_prefix and
            # TieredKvCache.__contains__ are strictly READ-ONLY (no LRU
            # reorder), which is what makes the unlocked probe safe under
            # the GIL — do not swap in tiered.lookup() (it mutates LRU
            # order) without adding a lock.
            host = core.tiered
            prefix_hit = core.pool.probe_prefix(
                bi.token_ids, (lambda h: h in host) if host else None,
                # kv_salt: the salted chain VLM blocks are actually stored
                # under (falls back to lora_id for text-only requests)
                lora_id=bi.kv_salt or bi.lora_id)
            remote = False
            if drouter.length_exceeds_local(len(bi.token_ids), prefix_hit):
                # only candidates pay the queue-depth RPC
                qsize = await queue.size()
                remote = drouter.should_prefill_remote(
                    len(bi.token_ids), prefix_hit, qsize)
            tracer = tracing.get_tracer()
            if remote:
                # layer-streamed ingest (DYN_KV_STREAM): hand the receiver
                # an engine handle so each arriving layer's device scatter
                # is enqueued while later layers are still on the wire —
                # the future then resolves to the handle (not arrays) once
                # the final scatter is enqueued, never synced
                ingest = None
                if stream_enabled() and hasattr(engine, "kv_ingest"):
                    ingest = engine.kv_ingest(bi, ctx.id)
                # register interest BEFORE enqueueing: a fast prefill worker
                # may push the KV back before we'd otherwise start listening
                fut = receiver.expect(ctx.id, ingest=ingest)
                async with tracer.span("prefill.remote_wait",
                                       trace_id=ctx.id,
                                       prompt_tokens=len(bi.token_ids),
                                       prefix_hit_tokens=prefix_hit) as wsp:
                    remote_t0 = time.monotonic()
                    try:
                        await queue.enqueue(RemotePrefillRequest(
                            ctx.id, drt.worker_id, request,
                            deadline=ctx.deadline,
                            priority=ctx.priority))
                    except overload.OverloadError as e:
                        # bounded-queue / predictive shed at enqueue: the
                        # remote path is refused in milliseconds; local
                        # prefill (deadline-bounded) takes over
                        receiver.abandon(ctx.id)
                        log.info("prefill enqueue shed for %s (%s); "
                                 "prefilling locally", ctx.id, e.reason)
                        kv = None
                    else:
                        try:
                            kv = await await_remote_kv(ctx, fut)
                        except RemotePrefillError as e:
                            log.warning("remote prefill for %s dead-"
                                        "lettered (%s); prefilling "
                                        "locally", ctx.id, e)
                            kv = None
                        if kv is not None:
                            # the predictive shed needs PER-ITEM service
                            # time; the observed turnaround includes the
                            # queue wait behind ~qsize earlier jobs, so
                            # normalize by the depth seen at the remote
                            # decision — feeding raw turnaround would
                            # double-count the queue and self-reinforce
                            # (deeper queue -> bigger estimate -> shed)
                            queue.observe_service(
                                (time.monotonic() - remote_t0)
                                / max(qsize + 1, 1))
                    if wsp is not None:
                        wsp.attrs["fallback_local"] = kv is None
                        wsp.attrs["streamed"] = kv is not None \
                            and kv is ingest
                if kv is not None and ingest is not None and kv is ingest:
                    # the sequence is already entering decode; consume
                    # its output queue. An engine-side ingest failure
                    # surfaces BEFORE the first token as a typed error —
                    # fall through to local prefill, never a user error
                    try:
                        async with tracer.span("decode.stream",
                                               trace_id=ctx.id,
                                               injected=True,
                                               streamed=True):
                            async for out in engine.generate_streamed(
                                    bi, ctx, ingest):
                                yield out.to_dict()
                        return
                    except RemotePrefillError as e:
                        log.warning("streamed KV ingest for %s failed "
                                    "(%s); prefilling locally", ctx.id, e)
                        kv = None
                if kv is not None:
                    k, v, tok, logp = kv
                    async with tracer.span("decode.stream",
                                           trace_id=ctx.id, injected=True):
                        async for out in engine.generate_prefilled(
                                bi, ctx, k, v, tok, logp):
                            yield out.to_dict()
                    return
            async with tracer.span("decode.stream", trace_id=ctx.id,
                                   injected=False):
                async for out in engine.generate(bi, ctx):
                    yield out.to_dict()

        await endpoint.serve(generate_handler)
    else:
        # model mobility (simple path only: no disagg/cluster/multihost —
        # those keep the plain cold-spawn wake): handlers stream through
        # an EngineRef so a cold-reload fallback can rebind the engine
        if core is not None and not multihost and cluster is None:
            from ..fleet.mobility import EngineRef

            engine_ref = EngineRef(engine)
        base = engine_ref if engine_ref is not None else engine
        served = (base if gate is None
                  else overload.SlotGatedEngine(base, gate))
        if cluster is not None:
            # prefetch wraps OUTSIDE the slot gate: the peer fetch overlaps
            # the queue wait instead of holding a slot while blocks
            # stream, and the local-tier h2d prefetch uploads matched
            # blocks to device staging during the same wait
            served = cluster.wrap(
                served, prefetcher=getattr(engine, "prefetch_tiers", None))
        await serve_core_engine(endpoint, served)
    if args.register_model:
        await register_model(drt.store, card, endpoint.path,
                             model_type="chat", lease=drt.lease)
        await register_model(drt.store, card, endpoint.path,
                             model_type="completion", lease=drt.lease)

    # --- metrics loop -------------------------------------------------
    from ..llm.metrics_aggregator import StagePublisher

    stage_pub = StagePublisher(drt.store, args.namespace, args.component,
                               drt.worker_id, drt.lease)

    # --- model mobility agent (simple path only) ---------------------
    mobility = None
    if engine_ref is not None:
        from ..fleet.mobility import MobilityAgent

        async def _reregister(payload):
            """Post-swap identity change: fresh lease (prepare_drain
            revoked the old one), serve ``generate`` under the new
            model's component, re-advertise the model, and move the
            metrics/KV-event identity along."""
            nonlocal component, card, stage_pub
            import os

            drt.lease = await drt.store.lease_grant(
                ttl=float(os.environ.get("DYN_LEASE_TTL", "10.0")))
            drt.worker_id = drt.lease
            drt.draining.clear()
            if token is not None:
                drt.store.on_lease_lost = _lease_lost
            args.component = payload.get("component") or args.component
            args.model_path = payload.get("model_path") or args.model_path
            args.model_name = payload.get("model") or args.model_name
            component = ns.component(args.component)
            pub.worker_id = drt.worker_id
            card = _build_card(args)
            await serve_core_engine(component.endpoint("generate"),
                                    served)
            if args.register_model:
                ep_path = component.endpoint("generate").path
                await register_model(drt.store, card, ep_path,
                                     model_type="chat", lease=drt.lease)
                await register_model(drt.store, card, ep_path,
                                     model_type="completion",
                                     lease=drt.lease)
            stage_pub = StagePublisher(drt.store, args.namespace,
                                       args.component, drt.worker_id,
                                       drt.lease)
            log.info("worker %x re-registered as %s (%s)",
                     drt.worker_id, args.model_name, args.component)

        async def _cold_reload(new_cfg):
            """Typed swap-fallback: rebuild the engine off-loop (the
            weight load can exceed the lease TTL) and re-attach the KV
            event hooks. The EngineRef rebinding is the agent's job."""
            nonlocal engine, core
            from ..engine.engine import JaxEngine

            old = engine_ref.engine

            def _build():
                try:
                    old.shutdown()
                except Exception:  # noqa: BLE001 - the reload must
                    log.exception("engine shutdown during reload")
                return JaxEngine(new_cfg)

            new_engine = await asyncio.get_running_loop(
                ).run_in_executor(None, _build)
            engine = new_engine
            core = new_engine.core
            core.pool.on_block_sealed = pub.block_stored
            core.pool.on_blocks_removed = pub.blocks_removed
            return new_engine

        mobility = await MobilityAgent(
            drt, args.namespace, args.component, engine_ref,
            reregister=_reregister, cold_reload=_cold_reload,
            model_name=args.model_name or "").start()

    async def metrics_loop():
        while True:
            # recomputed per beat: a model swap moves this worker to a
            # new component + lease mid-life
            key = metrics_key(args.namespace, args.component,
                              drt.worker_id)
            if core is not None:
                m = ForwardPassMetrics(**core.utilization())
            else:
                # echo engine: real in-flight count (the planner's
                # occupancy signal), capacity from --echo-slots
                m = ForwardPassMetrics(
                    request_active_slots=len(drt._active),
                    request_total_slots=getattr(args, "echo_slots", 64))
            try:
                await drt.store.put(key, json.dumps(m.to_dict()).encode(),
                                    lease=drt.lease)
                await stage_pub.publish()
            except StoreError:
                # store mid-outage (reconnect in flight): skip the beat —
                # the session replay re-puts the last snapshot anyway
                log.debug("metrics publish skipped (store disconnected)")
            except Exception:
                log.exception("stage metrics publish failed")
            await asyncio.sleep(args.metrics_interval)

    mtask = asyncio.create_task(metrics_loop())
    log.info("worker %x serving %s", drt.worker_id, endpoint.path)
    print(f"worker {drt.worker_id:x} serving {endpoint.path}", flush=True)
    if ready_event is not None:
        ready_event.set()
    try:
        if token is not None:
            await token.wait()     # Worker shell: serve until shutdown signal
        else:
            while True:
                await asyncio.sleep(3600)
    finally:
        # never leave the lease-lost closure pointing at a token the
        # caller may repurpose after this worker exits (shared-drt case)
        drt.store.on_lease_lost = None
        mtask.cancel()
        await obs_handle.stop()
        try:
            await span_sink.stop()
        except Exception:
            log.warning("span sink final flush failed; tail spans lost",
                        exc_info=True)
        await pub.stop()
        # deregistration cleanup: drop the published metric snapshots and
        # this engine's per-worker gauge series so aggregators/dyntop stop
        # rendering a ghost worker when the process (or a shared runtime)
        # outlives this serve loop
        from ..llm.metrics_aggregator import clear_worker_keys

        await clear_worker_keys(drt.store, args.namespace, args.component,
                                drt.worker_id)
        if cluster is not None:
            try:
                await cluster.stop()   # cancel publisher, drop registry key
            except Exception:
                log.warning("kv-cluster detach failed", exc_info=True)
        if mobility is not None:
            mobility.cache.close()     # drop pinned host weight trees
        if core is not None:
            try:
                engine.shutdown()   # joins the engine thread, clears gauges
            except Exception:
                log.exception("engine shutdown failed")
        if own_drt:
            await drt.close()


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dynamo-worker")
    p.add_argument("--engine", choices=("jax", "echo"), default="jax")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--advertise-host", default=None)
    p.add_argument("--model-path", default=None)
    p.add_argument("--model-name", default=None)
    p.add_argument("--register-model", action="store_true")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--kv-block-size", type=int, default=64)
    p.add_argument("--metrics-interval", type=float, default=1.0)
    p.add_argument("--echo-slots", type=int, default=64,
                   help="advertised request slots of the echo engine "
                        "(its occupancy signal for the planner)")
    p.add_argument("--enable-disagg", action="store_true",
                   help="decode role: remote-prefill long cold prompts")
    p.add_argument("--max-local-prefill-length", type=int, default=1000)
    p.add_argument("--max-prefill-queue-size", type=int, default=2)
    p.add_argument("--remote-prefill-timeout", type=float, default=120.0)
    p.add_argument("--extra-engine-args", default=None,
                   help="inline JSON engine kwargs")
    # multi-host slice (one process per TPU host; rank 0 is the leader)
    p.add_argument("--num-nodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--coordinator", default="127.0.0.1:9731",
                   help="jax.distributed coordinator host:port")
    p.add_argument("--dispatch-port", type=int, default=9732,
                   help="leader's dispatch-replay channel port")
    return p.parse_args(argv)


def main() -> None:
    from ..utils.logging_ext import init_logging
    from ..utils.hostmesh import honor_jax_platforms_env

    init_logging()
    honor_jax_platforms_env()
    args = parse_args()
    if args.num_nodes > 1 and args.node_rank > 0:
        run_follower(args)
        return
    # Worker shell: SIGINT/SIGTERM cancel the root token, in-flight requests
    # get stop (then kill after the grace window), leases revoke on close
    from ..runtime.worker import Worker

    shell = Worker()

    async def app(token):
        drt = await _connect_drt(args)
        shell.add_runtime(drt)
        await run_worker(args, drt=drt, token=token)

    try:
        shell.execute(app)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
