"""dyntop: the operator's single pane of glass — a live cluster view.

    python -m dynamo_tpu.cli.dyntop --store 127.0.0.1:4222 \
        [--namespace dynamo] [--component backend --component prefill] \
        [--interval 1.0] [--once] [--plain]

Reads the same planes the metrics aggregator and the planner's signal
collector read — per-worker ``ForwardPassMetrics`` snapshots under
``metrics/`` and the stage-histogram dumps under ``metrics_stage/`` — and
renders, per worker: active/total slots, KV occupancy, prefix hit rate,
MFU / MBU / achieved HBM GB/s, spec accept rate, and circuit-breaker
state; plus cluster-level TTFT/ITL p90, prefill queue depth, compile
counters, and SLO burn rates (when ``DYN_SLO_*`` objectives are set).

Renders with curses when stdout is a TTY (plain ANSI-refresh otherwise or
with ``--plain``); ``--once`` prints a single snapshot and exits (what the
loopback smoke test drives).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..utils.dynconfig import EnvDefaultsParser


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dyntop")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", action="append", default=None,
                   help="worker component to watch (repeatable; "
                        "default: backend + prefill)")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--plain", action="store_true",
                   help="force plain-refresh output (no curses)")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# collection (one store round-trip set per refresh)
# ---------------------------------------------------------------------------
class ClusterSnapshotter:
    """Assembles one renderable snapshot per tick from the store planes.
    Owns an :class:`~dynamo_tpu.utils.slo.SloMonitor` so burn rates
    accumulate across refreshes."""

    def __init__(self, store, namespace: str, components: List[str]):
        from ..utils.slo import SloMonitor

        self.store = store
        self.namespace = namespace
        self.components = list(components)
        # gauge=None: dyntop observes, it does not export
        self.slo = SloMonitor(registry_gauge=None)

    async def collect(self) -> Dict:
        from ..llm.disagg import prefill_queue_names
        from ..llm.metrics_aggregator import (fetch_stage_states,
                                              fetch_worker_metrics)
        from ..planner.signals import open_instance_ids, quantile_from_states
        from ..utils.overload import (admission_depth_total,
                                      brownout_level_from_states,
                                      shed_totals)

        states = await fetch_stage_states(self.store, self.namespace)
        workers: Dict[str, Dict] = {}
        for comp in self.components:
            workers[comp] = await fetch_worker_metrics(
                self.store, self.namespace, comp)
        q_depth = 0
        for qname in prefill_queue_names(self.namespace):
            try:
                q_depth += await self.store.q_len(qname)
            except Exception:  # noqa: BLE001 - queue plane optional
                pass
        burn = self.slo.observe(states) if self.slo.objectives else {}
        overload = {
            "brownout": brownout_level_from_states(states),
            "shed_total": shed_totals(states),
            "admission_depth": admission_depth_total(states),
        }
        return {
            "at": time.time(),
            "namespace": self.namespace,
            "workers": workers,
            "breaker_open": open_instance_ids(states),
            "ttft_p90": quantile_from_states(states, "llm_ttft_seconds",
                                             0.90),
            "itl_p90": quantile_from_states(states,
                                            "llm_inter_token_seconds", 0.90),
            "prefill_queue": q_depth,
            "compiles": _compile_totals(states),
            "slo_burn": burn,
            "overload": overload,
        }


def _compile_totals(states) -> Dict[str, Tuple[float, float]]:
    """{kind: (programs, seconds)} summed across every published dump."""
    progs: Dict[str, float] = {}
    secs: Dict[str, float] = {}
    for _component, dump in states:
        for name, acc in (("dyn_compiled_programs", progs),
                          ("dyn_compile_seconds_total", secs)):
            st = dump.get(name)
            if not st or st.get("kind") != "counter":
                continue
            for skey, val in st.get("series", {}).items():
                kind = skey.split("\x1f")[0] if skey else "?"
                acc[kind] = acc.get(kind, 0.0) + val
    return {k: (progs.get(k, 0.0), secs.get(k, 0.0))
            for k in sorted(set(progs) | set(secs))}


# ---------------------------------------------------------------------------
# rendering (pure; unit-tested)
# ---------------------------------------------------------------------------
def _fmt(v: Optional[float], spec: str = "5.3f", na: str = "    -") -> str:
    return na if v is None else format(v, spec)


def render(snap: Dict) -> str:
    lines: List[str] = []
    hdr = (f"dyntop — ns={snap['namespace']}  "
           f"ttft_p90={_fmt(snap.get('ttft_p90'))}s  "
           f"itl_p90={_fmt(snap.get('itl_p90'))}s  "
           f"prefill_q={snap.get('prefill_queue', 0)}")
    lines.append(hdr)
    comps = snap.get("compiles") or {}
    if comps:
        lines.append("compiles: " + "  ".join(
            f"{k}={int(n)} ({s:.1f}s)" for k, (n, s) in comps.items()))
    for slo, per_w in (snap.get("slo_burn") or {}).items():
        burns = "  ".join(f"{int(w)}s={b:.2f}" for w, b in
                          sorted(per_w.items()))
        worst = max(per_w.values()) if per_w else 0.0
        flag = "  BREACH" if worst > 1.0 else ""
        lines.append(f"slo {slo}: burn {burns}{flag}")
    ov = snap.get("overload") or {}
    if any(ov.get(k) for k in ("brownout", "shed_total",
                               "admission_depth")):
        from ..utils.overload import LEVEL_NAMES

        lvl = int(ov.get("brownout", 0))
        lines.append(
            f"overload: brownout=L{lvl} ({LEVEL_NAMES.get(lvl, '?')})  "
            f"shed={int(ov.get('shed_total', 0))}  "
            f"admit_q={int(ov.get('admission_depth', 0))}")
    lines.append(
        f"{'worker':>10} {'comp':<9} {'slots':>7} {'kv%':>5} {'hit%':>5} "
        f"{'mfu%':>6} {'mbu%':>6} {'GB/s':>7} {'spec%':>6} {'brk':>4}")
    open_set = snap.get("breaker_open") or set()
    n = 0
    for comp, workers in sorted((snap.get("workers") or {}).items()):
        for wid, m in sorted(workers.items()):
            n += 1
            kv = (100.0 * m.kv_active_blocks / m.kv_total_blocks
                  if m.kv_total_blocks else 0.0)
            brk = "OPEN" if f"{wid:x}" in open_set else "ok"
            lines.append(
                f"{wid:>10x} {comp:<9} "
                f"{int(m.request_active_slots):>3}/{int(m.request_total_slots):<3} "
                f"{kv:>5.1f} {100.0 * m.gpu_prefix_cache_hit_rate:>5.1f} "
                f"{100.0 * m.mfu:>6.2f} {100.0 * m.mbu:>6.2f} "
                f"{m.hbm_gbps:>7.2f} {100.0 * m.spec_accept_rate:>6.1f} "
                f"{brk:>4}")
    if not n:
        lines.append("(no live workers publishing metrics)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
async def run_once(args) -> str:
    from ..runtime.store_client import StoreClient

    host, port = args.store.split(":")
    store = StoreClient(host, int(port))
    await store.connect()
    try:
        snap = await ClusterSnapshotter(
            store, args.namespace,
            args.component or ["backend", "prefill"]).collect()
        return render(snap)
    finally:
        await store.close()


async def _loop_plain(args) -> None:
    from ..runtime.store_client import StoreClient

    host, port = args.store.split(":")
    store = StoreClient(host, int(port))
    await store.connect()
    snapper = ClusterSnapshotter(store, args.namespace,
                                 args.component or ["backend", "prefill"])
    try:
        while True:
            text = render(await snapper.collect())
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J")   # home + clear
            sys.stdout.write(text + "\n")
            sys.stdout.flush()
            await asyncio.sleep(args.interval)
    finally:
        await store.close()


async def _loop_curses(args) -> None:
    import curses

    from ..runtime.store_client import StoreClient

    host, port = args.store.split(":")
    store = StoreClient(host, int(port))
    await store.connect()
    snapper = ClusterSnapshotter(store, args.namespace,
                                 args.component or ["backend", "prefill"])
    scr = curses.initscr()
    curses.noecho()
    curses.cbreak()
    scr.nodelay(True)
    try:
        while True:
            text = render(await snapper.collect())
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for i, line in enumerate(text.splitlines()[:maxy - 1]):
                scr.addnstr(i, 0, line, maxx - 1)
            scr.refresh()
            if scr.getch() in (ord("q"), 27):   # q / ESC
                return
            await asyncio.sleep(args.interval)
    finally:
        curses.nocbreak()
        curses.echo()
        curses.endwin()
        await store.close()


def main() -> None:
    from ..utils.logging_ext import init_logging

    init_logging()
    args = parse_args()
    try:
        if args.once:
            print(asyncio.run(run_once(args)))
        elif args.plain or not sys.stdout.isatty():
            asyncio.run(_loop_plain(args))
        else:
            try:
                asyncio.run(_loop_curses(args))
            except Exception as e:
                # a terminal curses can't drive falls back to plain
                print(f"(curses UI unavailable: {e!r}; plain mode)",
                      file=sys.stderr)
                asyncio.run(_loop_plain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
