"""dyntop: the operator's single pane of glass — a live cluster view.

    python -m dynamo_tpu.cli.dyntop --store 127.0.0.1:4222 \
        [--namespace dynamo] [--component backend --component prefill] \
        [--interval 1.0] [--once] [--plain]

Reads the same planes the metrics aggregator and the planner's signal
collector read — per-worker ``ForwardPassMetrics`` snapshots under
``metrics/`` and the stage-histogram dumps under ``metrics_stage/`` — and
renders, per worker: active/total slots, KV occupancy, prefix hit rate,
MFU / MBU / achieved HBM GB/s, spec accept rate, and circuit-breaker
state; plus cluster-level TTFT/ITL p90, prefill queue depth, compile
counters, and SLO burn rates (when ``DYN_SLO_*`` objectives are set).
The coordination store renders as its own ``store:`` line (op/s, p99 of
the hottest keyspace family, watches/leases/conns, watch fan-out/s,
telemetry drops) from the dump it publishes about itself;
``--store-detail`` expands it into a per-family table.

Renders with curses when stdout is a TTY (plain ANSI-refresh otherwise or
with ``--plain``); ``--once`` prints a single snapshot and exits (what the
loopback smoke test drives).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.dynconfig import EnvDefaultsParser
from ..utils.prometheus import hist_quantile


def parse_args(argv=None) -> argparse.Namespace:
    p = EnvDefaultsParser(prog="dyntop")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", action="append", default=None,
                   help="worker component to watch (repeatable; "
                        "default: backend + prefill)")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--plain", action="store_true",
                   help="force plain-refresh output (no curses)")
    p.add_argument("--store-detail", action="store_true",
                   help="expand the store: line into a per-keyspace-"
                        "family table (ops, p99, resident keys/bytes, "
                        "queue depth)")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# collection (one store round-trip set per refresh)
# ---------------------------------------------------------------------------
class ClusterSnapshotter:
    """Assembles one renderable snapshot per tick from the store planes.
    Owns an :class:`~dynamo_tpu.utils.slo.SloMonitor` so burn rates
    accumulate across refreshes."""

    def __init__(self, store, namespace: str, components: List[str]):
        from ..utils.slo import SloMonitor

        self.store = store
        self.namespace = namespace
        self.components = list(components)
        # gauge=None: dyntop observes, it does not export
        self.slo = SloMonitor(registry_gauge=None)
        # previous refresh's store totals (monotonic, ops_total,
        # fanout_total, per-family bucket counts): differentiated into
        # the store line's op/s, fan-out/s, and windowed hot-family p99
        self._store_prev: Optional[Dict] = None

    async def collect(self) -> Dict:
        from ..llm.disagg import prefill_queue_names
        from ..llm.metrics_aggregator import (fetch_stage_states_ex,
                                              fetch_worker_metrics)
        from ..planner.signals import open_instance_ids, quantile_from_states
        from ..utils.overload import (admission_depth_total,
                                      brownout_level_from_states,
                                      shed_totals)

        states, regional = await fetch_stage_states_ex(self.store,
                                                       self.namespace)
        # fleet plane: per-model pool rows (and their components join the
        # worker table automatically — a fleet's pools are per-model, so
        # a static --component list would render an empty fleet)
        from ..fleet.registry import fetch_fleet_status, list_fleet_models

        fleet: Dict[str, Dict] = {}
        try:
            specs = await list_fleet_models(self.store, self.namespace)
            if specs:
                status = await fetch_fleet_status(self.store,
                                                  self.namespace)
                for s in specs:
                    fleet[s.name] = {
                        "component": s.component,
                        "min": s.min_replicas, "max": s.max_replicas,
                        "priority": s.priority,
                        "chips_per_replica": s.chips_per_replica,
                        **(status.get(s.name) or {"state": "unreconciled"}),
                    }
        except Exception:  # noqa: BLE001 - fleet plane optional
            pass
        components = list(self.components)
        for f in fleet.values():
            if f["component"] not in components:
                components.append(f["component"])
        workers: Dict[str, Dict] = {}
        if regional is not None:
            # region path: per-worker ForwardPassMetrics ride the region
            # records — zero per-component store scans, and components
            # the aggregators found join the table automatically
            for comp in set(components) | set(regional.fpm):
                workers[comp] = regional.workers_for(comp)
        else:
            for comp in components:
                workers[comp] = await fetch_worker_metrics(
                    self.store, self.namespace, comp)
        q_depth = 0
        for qname in prefill_queue_names(self.namespace):
            try:
                q_depth += await self.store.q_len(qname)
            except Exception:  # noqa: BLE001 - queue plane optional
                pass
        store_stats = store_stats_from_states(states)
        if store_stats is not None:
            # fleet-side telemetry-pipeline losses ride the same dumps
            for name, key in (("dyn_spans_dropped_total", "span_drops"),
                              ("dyn_spans_sampled_out_total",
                               "spans_sampled_out")):
                tot = 0.0
                for _comp, dump in states:
                    st = dump.get(name) or {}
                    tot += sum((st.get("series") or {}).values())
                store_stats[key] = tot
            now = time.monotonic()
            prev = self._store_prev
            fam_counts = store_stats.pop("_fam_counts", {})
            buckets = store_stats.pop("_buckets", None)
            if prev is not None and now > prev["t"]:
                dt = now - prev["t"]
                store_stats["op_rate"] = max(
                    store_stats["ops_total"] - prev["ops"], 0.0) / dt
                store_stats["fanout_rate"] = max(
                    store_stats["fanout_total"] - prev["fanout"], 0.0) / dt
                # windowed per-family view (this refresh only): an
                # incident-slow store must move the rendered hot/p99
                # immediately, not after it outweighs the lifetime counts
                window: Dict[str, Dict] = {}
                for fam, cur in fam_counts.items():
                    base = prev["fams"].get(fam)
                    d_ops = cur["ops"] - (base["ops"] if base else 0)
                    if d_ops <= 0:
                        continue
                    d_counts = [x - y for x, y in zip(
                        cur["counts"] or [], base["counts"] or [])] \
                        if base else cur["counts"]
                    window[fam] = {
                        "ops": d_ops,
                        "p99_s": hist_quantile(buckets, d_counts,
                                               d_ops, 0.99)}
                store_stats["families_window"] = window
            self._store_prev = {"t": now,
                                "ops": store_stats["ops_total"],
                                "fanout": store_stats["fanout_total"],
                                "fams": fam_counts}
        # sharded store: every shard publishes its own self-dump under
        # the same metrics_stage/_store/ key in its own KV — read each
        # shard's copy for the --store-detail per-shard rows
        store_shards: Optional[Dict[str, Optional[Dict]]] = None
        shard_of_family: Dict[str, str] = {}
        if hasattr(self.store, "get_prefix_on"):
            from ..llm.metrics_aggregator import (STORE_STAGE_PREFIX,
                                                  merge_stage_items)

            store_shards = {}
            for i, name in enumerate(self.store.shard_names):
                try:
                    items = await self.store.get_prefix_on(
                        i, STORE_STAGE_PREFIX)
                except Exception:  # noqa: BLE001 - a dead shard renders
                    # as such instead of blanking the whole table
                    store_shards[name] = None
                    continue
                sstates = [(d.get("component") or "store", m)
                           for _k, (d, m) in
                           merge_stage_items(items).items()]
                st = store_stats_from_states(sstates)
                if st is not None:
                    st.pop("_fam_counts", None)
                    st.pop("_buckets", None)
                store_shards[name] = st
            for fam, idx in self.store.fam_map.items():
                shard_of_family[fam] = self.store.shard_names[idx]
        # live incident beacons (flight-recorder capture plane)
        incidents: List[Dict] = []
        try:
            from ..obs.incidents import list_incidents

            incidents = await list_incidents(self.store, self.namespace)
        except Exception:  # noqa: BLE001 - incident plane optional
            pass
        burn = self.slo.observe(states) if self.slo.objectives else {}
        overload = {
            "brownout": brownout_level_from_states(states),
            "shed_total": shed_totals(states),
            "admission_depth": admission_depth_total(states),
        }
        from ..obs.flows import flows_from_states

        return {
            "cluster": cluster_kv_totals(states),
            "transfer": transfer_totals(states),
            "links": flows_from_states(states),
            "paging": kvpage_totals(states),
            "fleet": fleet,
            "at": time.time(),
            "namespace": self.namespace,
            "regions": regional.meta if regional is not None else None,
            "store": store_stats,
            "store_shards": store_shards,
            "shard_of_family": shard_of_family,
            "workers": workers,
            "breaker_open": open_instance_ids(states),
            "ttft_p90": quantile_from_states(states, "llm_ttft_seconds",
                                             0.90),
            "itl_p90": quantile_from_states(states,
                                            "llm_inter_token_seconds", 0.90),
            "prefill_queue": q_depth,
            "compiles": _compile_totals(states),
            "slo_burn": burn,
            "overload": overload,
            "incidents": incidents,
        }


def store_stats_from_states(states) -> Optional[Dict]:
    """The store server's self-telemetry, extracted from one
    ``fetch_stage_states`` result (the ``component="store"`` dump the
    server writes into its own KV). Returns cumulative totals; the
    snapshotter differentiates successive calls into op/s and fan-out/s.
    None when no store dump is being published (old store, or
    ``DYN_STORE_METRICS_INTERVAL=0``)."""
    from ..utils.prometheus import merge_state_dumps

    dumps = [d for comp, d in states
             if comp == "store" and "dyn_store_op_seconds" in d]
    if not dumps:
        return None
    # a sharded store surfaces one dump per shard: the store: line shows
    # their sum (the per-shard split lives in --store-detail)
    dump = dumps[0] if len(dumps) == 1 else merge_state_dumps(dumps)

    def gauge(name: str) -> float:
        st = dump.get(name) or {}
        return float(sum((st.get("series") or {}).values()) or 0.0)

    ops = dump["dyn_store_op_seconds"]
    fams: Dict[str, Dict] = {}
    for skey, val in (ops.get("series") or {}).items():
        parts = skey.split("\x1f")
        fam = parts[1] if len(parts) > 1 else "?"
        agg = fams.setdefault(fam, {"ops": 0, "counts": None})
        agg["ops"] += val.get("total", 0)
        counts = val.get("counts") or []
        if agg["counts"] is None:
            agg["counts"] = list(counts)
        else:
            agg["counts"] = [a + b for a, b in zip(agg["counts"], counts)]
    families = {
        fam: {"ops": a["ops"],
              "p99_s": hist_quantile(ops.get("buckets"), a["counts"],
                                     a["ops"], 0.99)}
        for fam, a in fams.items()}
    per_fam_gauges = {}
    for name, field in (("dyn_store_keys", "keys"),
                        ("dyn_store_bytes", "bytes"),
                        ("dyn_store_queue_depth", "queue_depth")):
        st = dump.get(name) or {}
        for skey, val in (st.get("series") or {}).items():
            fam = skey.split("\x1f")[0] if skey else "?"
            per_fam_gauges.setdefault(fam, {})[field] = val
    return {
        "ops_total": sum(f["ops"] for f in families.values()),
        "families": families,
        # raw per-family bucket counts + edges: the snapshotter diffs
        # successive refreshes into the windowed hot-family/p99 the
        # store: line shows (cumulative p99 barely moves in an incident)
        "_fam_counts": {fam: {"ops": a["ops"], "counts": a["counts"]}
                        for fam, a in fams.items()},
        "_buckets": ops.get("buckets"),
        "family_gauges": per_fam_gauges,
        "watches": gauge("dyn_store_watches"),
        "leases": gauge("dyn_store_leases"),
        "conns": gauge("dyn_store_conns"),
        "keys_total": gauge("dyn_store_keys"),
        "bytes_total": gauge("dyn_store_bytes"),
        "fanout_total": gauge("dyn_store_watch_fanout_total"),
        "drops": gauge("dyn_store_fanout_drops_total"),
    }


def cluster_kv_totals(states) -> Dict[str, float]:
    """Fleet-summed KV tier + cluster-sharing counters from one
    ``fetch_stage_states`` result — the ``cluster:`` line's numbers.
    All-zero when the plane is off (nothing rendered then)."""
    names = {
        "dyn_kv_tier_hits_total": "tier_hits",
        "dyn_kv_tier_misses_total": "tier_misses",
        "dyn_kv_cluster_hits_total": "hits",
        "dyn_kv_cluster_fetches_total": "fetches",
        "dyn_kv_cluster_fallbacks_total": "fallbacks",
    }
    out = {v: 0.0 for v in names.values()}
    out["tier_blocks"] = 0.0
    for _component, dump in states:
        for metric, field in names.items():
            st = dump.get(metric) or {}
            out[field] += sum((st.get("series") or {}).values())
        st = dump.get("dyn_kv_tier_blocks") or {}
        out["tier_blocks"] += sum((st.get("series") or {}).values())
    return out


def transfer_totals(states) -> Dict[str, Any]:
    """Fleet-summed KV-movement plane: bytes moved, streamed-ingest
    counters, h2d-prefetch hit/stall counters, and the per-(src,dst)
    bandwidth gauge folded to (pairs, min, max) — the ``transfer:``
    line's numbers. All-zero when nothing has moved (line not
    rendered)."""
    names = {
        "dyn_kv_stream_ingests_total": "stream_ingests",
        "dyn_kv_stream_fallbacks_total": "stream_fallbacks",
        "dyn_prefetch_h2d_hits_total": "prefetch_hits",
        "dyn_prefetch_h2d_stalls_total": "prefetch_stalls",
    }
    out: Dict[str, Any] = {v: 0.0 for v in names.values()}
    out["bytes"] = 0.0
    bws: List[float] = []
    for _component, dump in states:
        for metric, field in names.items():
            st = dump.get(metric) or {}
            out[field] += sum((st.get("series") or {}).values())
        # every transfer is counted by BOTH ends (send+recv pairs): sum
        # only the receive-side directions so moved= reports each byte
        # once
        st = dump.get("llm_kv_transfer_bytes_total") or {}
        for skey, val in (st.get("series") or {}).items():
            if skey in ("recv", "cluster_recv"):
                out["bytes"] += val
        st = dump.get("llm_kv_pair_bw_bytes_per_s") or {}
        bws.extend(v for v in (st.get("series") or {}).values() if v > 0)
    out["pairs"] = float(len(bws))
    out["bw_min"] = min(bws) if bws else 0.0
    out["bw_max"] = max(bws) if bws else 0.0
    return out


def kvpage_totals(states) -> Dict[str, float]:
    """Fleet-summed KV-paging counters + resident bytes by tier — the
    ``paging:`` line. All-zero when no engine pages (nothing rendered)."""
    names = {
        "dyn_kvpage_demotions_total": "demotions",
        "dyn_kvpage_pageins_total": "pageins",
        "dyn_kvpage_faults_total": "faults",
    }
    out = {v: 0.0 for v in names.values()}
    out["device_bytes"] = 0.0
    out["host_bytes"] = 0.0
    for _component, dump in states:
        for metric, field in names.items():
            st = dump.get(metric) or {}
            out[field] += sum((st.get("series") or {}).values())
        st = dump.get("dyn_kvpage_resident_bytes") or {}
        for skey, val in (st.get("series") or {}).items():
            tier = skey.split("\x1f")[0] if skey else "?"
            key = "device_bytes" if tier == "device" else "host_bytes"
            out[key] += val
    return out


def _compile_totals(states) -> Dict[str, Tuple[float, float]]:
    """{kind: (programs, seconds)} summed across every published dump."""
    progs: Dict[str, float] = {}
    secs: Dict[str, float] = {}
    for _component, dump in states:
        for name, acc in (("dyn_compiled_programs", progs),
                          ("dyn_compile_seconds_total", secs)):
            st = dump.get(name)
            if not st or st.get("kind") != "counter":
                continue
            for skey, val in st.get("series", {}).items():
                kind = skey.split("\x1f")[0] if skey else "?"
                acc[kind] = acc.get(kind, 0.0) + val
    return {k: (progs.get(k, 0.0), secs.get(k, 0.0))
            for k in sorted(set(progs) | set(secs))}


# ---------------------------------------------------------------------------
# rendering (pure; unit-tested)
# ---------------------------------------------------------------------------
def _fmt(v: Optional[float], spec: str = "5.3f", na: str = "    -") -> str:
    return na if v is None else format(v, spec)


def _fmt_ms(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v == float("inf"):
        return ">tail"
    return f"{v * 1e3:.1f}ms"


def render(snap: Dict, store_detail: bool = False) -> str:
    lines: List[str] = []
    hdr = (f"dyntop — ns={snap['namespace']}  "
           f"ttft_p90={_fmt(snap.get('ttft_p90'))}s  "
           f"itl_p90={_fmt(snap.get('itl_p90'))}s  "
           f"prefill_q={snap.get('prefill_queue', 0)}")
    lines.append(hdr)
    st = snap.get("store")
    if st:
        # hot family + p99 from the last refresh window when available
        # (lifetime-cumulative counts barely move during an incident);
        # the --store-detail table below stays lifetime-cumulative
        fams = st.get("families_window") or st.get("families") or {}
        hot = max(fams, key=lambda f: fams[f]["ops"]) if fams else None
        rate = st.get("op_rate")
        rate_s = f"{rate:.0f}/s" if rate is not None \
            else f"{int(st['ops_total'])} ops"
        fan = st.get("fanout_rate")
        fan_s = f"{fan:.0f}/s" if fan is not None \
            else f"{int(st['fanout_total'])}"
        drops = int(st.get("drops", 0) + st.get("span_drops", 0))
        lines.append(
            f"store: ops={rate_s}"
            + (f"  p99[{hot}]={_fmt_ms(fams[hot]['p99_s'])}" if hot else "")
            + f"  watches={int(st['watches'])}"
            f"  leases={int(st['leases'])}  conns={int(st['conns'])}"
            f"  fanout={fan_s}  drops={drops}"
            f"  sampled_out={int(st.get('spans_sampled_out', 0))}")
        if store_detail:
            shard_of = snap.get("shard_of_family") or {}
            shard_col = bool(snap.get("store_shards"))
            hdr = (f"  {'family':<16} {'ops':>9} {'p99':>8} {'keys':>7} "
                   f"{'MiB':>8} {'qdepth':>6}")
            lines.append(hdr + (f" {'shard':>6}" if shard_col else ""))
            life = st.get("families") or {}   # lifetime totals here
            gauges = st.get("family_gauges") or {}
            for fam in sorted(set(life) | set(gauges)):
                f_ops = life.get(fam, {})
                g = gauges.get(fam, {})
                row = (
                    f"  {fam:<16} {int(f_ops.get('ops', 0)):>9} "
                    f"{_fmt_ms(f_ops.get('p99_s')):>8} "
                    f"{int(g.get('keys', 0)):>7} "
                    f"{g.get('bytes', 0) / 2**20:>8.2f} "
                    f"{int(g.get('queue_depth', 0)):>6}")
                if shard_col:
                    row += f" {shard_of.get(fam, 's0'):>6}"
                lines.append(row)
    shards = snap.get("store_shards")
    if shards and (store_detail or st is None):
        # per-shard store summary: each dynstore's own self-telemetry
        for name in sorted(shards):
            sd = shards[name]
            if sd is None:
                lines.append(f"  shard {name}: UNREACHABLE")
                continue
            fams = sd.get("families") or {}
            hot = max(fams, key=lambda f: fams[f]["ops"]) if fams else None
            lines.append(
                f"  shard {name}: ops={int(sd.get('ops_total', 0))}"
                + (f"  p99[{hot}]={_fmt_ms(fams[hot]['p99_s'])}"
                   if hot else "")
                + f"  keys={int(sd.get('keys_total', 0))}"
                f"  watches={int(sd.get('watches', 0))}"
                f"  leases={int(sd.get('leases', 0))}"
                f"  conns={int(sd.get('conns', 0))}")
    rg = snap.get("regions")
    if rg:
        lines.append(
            f"regions: aggs={rg.get('aggregators', 0)}"
            + (f"(+{rg['stale']} stale)" if rg.get("stale") else "")
            + f"  workers={rg.get('workers', 0)} "
            f"({rg.get('workers_min', 0)}..{rg.get('workers_max', 0)}"
            f"/region)  merge_p50={_fmt_ms(rg.get('merge_p50_s'))} "
            f"p99={_fmt_ms(rg.get('merge_p99_s'))}  "
            f"age_max={rg.get('age_max_s', 0.0):.1f}s")
    fleet = snap.get("fleet") or {}
    if fleet:
        lines.append("fleet:")
        lines.append(
            f"  {'model':<20} {'comp':<18} {'state':<11} {'repl':>9} "
            f"{'chips':>5} {'prio':>4} {'burn':>6} {'unsrv':>5} "
            f"{'wake':>10}")
        for name in sorted(fleet):
            f = fleet[name]
            repl = (f"{f.get('replicas', '?')}->{f.get('target', '?')}"
                    if f.get("target") is not None
                    else str(f.get("replicas", "?")))
            # last wake path (model mobility): swap = in-place weight
            # swap (seconds-scale), cold = full boot
            wake = "-"
            if f.get("wake_path"):
                secs = f.get("wake_seconds")
                wake = (f"{f['wake_path']}/{secs:.1f}s"
                        if isinstance(secs, (int, float))
                        else str(f["wake_path"]))
            lines.append(
                f"  {name:<20} {f.get('component', '?'):<18} "
                f"{f.get('state', '?'):<11} {repl:>9} "
                f"{f.get('chips', 0):>5} {f.get('priority', 0):>4} "
                f"{float(f.get('burn') or 0.0):>6.2f} "
                f"{int(f.get('unserved') or 0):>5} {wake:>10}")
    cl = snap.get("cluster") or {}
    if any(cl.values()):
        th, tm = cl.get("tier_hits", 0), cl.get("tier_misses", 0)
        hit_pct = 100.0 * th / (th + tm) if (th + tm) else 0.0
        lines.append(
            f"cluster: tier_blocks={int(cl.get('tier_blocks', 0))}  "
            f"tier_hit%={hit_pct:.1f}  "
            f"peer_hits={int(cl.get('hits', 0))}  "
            f"fetches={int(cl.get('fetches', 0))}  "
            f"fallbacks={int(cl.get('fallbacks', 0))}")
    # links: the byte-flow ledger's top-talker matrix. The summary line
    # absorbs the old transfer: line's counters; per-link rows render
    # only when workers actually publish flows (older workers without a
    # ledger degrade to the summary alone — absent entirely when nothing
    # has moved, never a crash).
    tr = snap.get("transfer") or {}
    links = snap.get("links") or []
    if any(tr.values()) or links:
        line = (f"links: moved={tr.get('bytes', 0.0) / 1e6:.0f}MB  "
                f"streamed={int(tr.get('stream_ingests', 0))}  "
                f"stream_fallbacks={int(tr.get('stream_fallbacks', 0))}  "
                f"prefetch_hits={int(tr.get('prefetch_hits', 0))}  "
                f"stalls={int(tr.get('prefetch_stalls', 0))}")
        if tr.get("pairs"):
            line += (f"  pairs={int(tr['pairs'])} "
                     f"bw={tr.get('bw_min', 0.0) / 1e6:.0f}"
                     f"..{tr.get('bw_max', 0.0) / 1e6:.0f}MB/s")
        lines.append(line)
        if links:
            from ..obs.flows import fmt_bytes

            lines.append(
                f"  {'link':<24} {'bytes':>9} {'bw':>10} {'sat':>6} "
                f"kinds")
            for e in links[:6]:
                sat = float(e.get("saturation") or 0.0)
                flag = "!" if e.get("congested") else ""
                kinds = ",".join(sorted(
                    e.get("kinds") or {},
                    key=lambda k: -e["kinds"][k])[:3])
                lines.append(
                    f"  {e['src'] + '>' + e['dst']:<24} "
                    f"{fmt_bytes(float(e.get('bytes') or 0)):>9} "
                    f"{float(e.get('bw') or 0.0) / 1e6:>8.1f}MB "
                    f"{sat:>5.2f}{flag:<1} {kinds}")
    pg = snap.get("paging") or {}
    if any(pg.values()):
        lines.append(
            f"paging: demoted={int(pg.get('demotions', 0))}  "
            f"pageins={int(pg.get('pageins', 0))}  "
            f"faults={int(pg.get('faults', 0))}  "
            f"resident={pg.get('device_bytes', 0.0) / 1e6:.0f}MB dev / "
            f"{pg.get('host_bytes', 0.0) / 1e6:.0f}MB host")
    comps = snap.get("compiles") or {}
    if comps:
        lines.append("compiles: " + "  ".join(
            f"{k}={int(n)} ({s:.1f}s)" for k, (n, s) in comps.items()))
    for slo, per_w in (snap.get("slo_burn") or {}).items():
        burns = "  ".join(f"{int(w)}s={b:.2f}" for w, b in
                          sorted(per_w.items()))
        worst = max(per_w.values()) if per_w else 0.0
        flag = "  BREACH" if worst > 1.0 else ""
        lines.append(f"slo {slo}: burn {burns}{flag}")
    ov = snap.get("overload") or {}
    if any(ov.get(k) for k in ("brownout", "shed_total",
                               "admission_depth")):
        from ..utils.overload import LEVEL_NAMES

        lvl = int(ov.get("brownout", 0))
        lines.append(
            f"overload: brownout=L{lvl} ({LEVEL_NAMES.get(lvl, '?')})  "
            f"shed={int(ov.get('shed_total', 0))}  "
            f"admit_q={int(ov.get('admission_depth', 0))}")
    inc = snap.get("incidents") or []
    if inc:
        latest = inc[0]           # list_incidents sorts newest first
        age = time.time() - latest.get("at", 0.0)
        lines.append(
            f"incidents: {len(inc)} live  latest={latest.get('id', '?')} "
            f"({latest.get('reason', '?')}, {age:.0f}s ago)  "
            f"-> ctl incident show {latest.get('id', '?')}")
    lines.append(
        f"{'worker':>10} {'comp':<9} {'slots':>7} {'kv%':>5} {'hit%':>5} "
        f"{'mfu%':>6} {'mbu%':>6} {'GB/s':>7} {'spec%':>6} {'brk':>4}")
    open_set = snap.get("breaker_open") or set()
    n = 0
    for comp, workers in sorted((snap.get("workers") or {}).items()):
        for wid, m in sorted(workers.items()):
            n += 1
            kv = (100.0 * m.kv_active_blocks / m.kv_total_blocks
                  if m.kv_total_blocks else 0.0)
            brk = "OPEN" if f"{wid:x}" in open_set else "ok"
            lines.append(
                f"{wid:>10x} {comp:<9} "
                f"{int(m.request_active_slots):>3}/{int(m.request_total_slots):<3} "
                f"{kv:>5.1f} {100.0 * m.gpu_prefix_cache_hit_rate:>5.1f} "
                f"{100.0 * m.mfu:>6.2f} {100.0 * m.mbu:>6.2f} "
                f"{m.hbm_gbps:>7.2f} {100.0 * m.spec_accept_rate:>6.1f} "
                f"{brk:>4}")
    if not n:
        lines.append("(no live workers publishing metrics)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
async def run_once(args) -> str:
    from ..runtime.scale.shards import make_store_client

    host, port = args.store.split(":")
    store = make_store_client(host, int(port))
    await store.connect()
    try:
        snap = await ClusterSnapshotter(
            store, args.namespace,
            args.component or ["backend", "prefill"]).collect()
        return render(snap, args.store_detail)
    finally:
        await store.close()


async def _loop_plain(args) -> None:
    from ..runtime.scale.shards import make_store_client

    host, port = args.store.split(":")
    store = make_store_client(host, int(port))
    await store.connect()
    snapper = ClusterSnapshotter(store, args.namespace,
                                 args.component or ["backend", "prefill"])
    try:
        while True:
            text = render(await snapper.collect(), args.store_detail)
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J")   # home + clear
            sys.stdout.write(text + "\n")
            sys.stdout.flush()
            await asyncio.sleep(args.interval)
    finally:
        await store.close()


async def _loop_curses(args) -> None:
    import curses

    from ..runtime.scale.shards import make_store_client

    host, port = args.store.split(":")
    store = make_store_client(host, int(port))
    await store.connect()
    snapper = ClusterSnapshotter(store, args.namespace,
                                 args.component or ["backend", "prefill"])
    scr = curses.initscr()
    curses.noecho()
    curses.cbreak()
    scr.nodelay(True)
    try:
        while True:
            text = render(await snapper.collect(), args.store_detail)
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for i, line in enumerate(text.splitlines()[:maxy - 1]):
                scr.addnstr(i, 0, line, maxx - 1)
            scr.refresh()
            if scr.getch() in (ord("q"), 27):   # q / ESC
                return
            await asyncio.sleep(args.interval)
    finally:
        curses.nocbreak()
        curses.echo()
        curses.endwin()
        await store.close()


def main() -> None:
    from ..utils.logging_ext import init_logging

    init_logging()
    args = parse_args()
    try:
        if args.once:
            print(asyncio.run(run_once(args)))
        elif args.plain or not sys.stdout.isatty():
            asyncio.run(_loop_plain(args))
        else:
            try:
                asyncio.run(_loop_curses(args))
            except Exception as e:
                # a terminal curses can't drive falls back to plain
                print(f"(curses UI unavailable: {e!r}; plain mode)",
                      file=sys.stderr)
                asyncio.run(_loop_plain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
