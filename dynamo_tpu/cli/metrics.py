"""Cluster metrics aggregator binary.

    python -m dynamo_tpu.cli.metrics --namespace dynamo \
        --component backend [--component prefill] --store localhost:4222 \
        --port 9091 [--scrape-interval 1.0]

Subscribes the namespace kv-hit-rate events, scrapes every worker's
ForwardPassMetrics from the store, and serves the cluster Prometheus gauges
(llm_kv_blocks_*, llm_requests_*_slots, llm_load_avg/std,
llm_kv_hit_rate_percent) on ``/metrics``.

Reference capability: the standalone metrics binary
(components/metrics/src/main.rs:115-241).
"""

from __future__ import annotations

import argparse

from ..utils.dynconfig import EnvDefaultsParser
import asyncio
import logging

from aiohttp import web

from ..llm.metrics_aggregator import ClusterMetricsAggregator
from ..runtime.component import DistributedRuntime

log = logging.getLogger("dynamo_tpu.cli.metrics")


def build_app(agg: ClusterMetricsAggregator) -> web.Application:
    async def metrics(request: web.Request) -> web.Response:
        return web.Response(text=agg.render(),
                            content_type="text/plain", charset="utf-8")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    return app


async def push_loop(agg: ClusterMetricsAggregator, url: str,
                    interval: float) -> None:
    """Pushgateway mode: PUT the rendered exposition text to ``url``
    every ``interval`` seconds (the reference binary's serve-or-push
    switch, components/metrics/src/main.rs:26-31)."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        while True:
            try:
                async with session.put(
                        url, data=agg.render().encode(),
                        headers={"Content-Type": "text/plain"}) as resp:
                    if resp.status >= 400:
                        log.warning("pushgateway %s returned %d", url,
                                    resp.status)
            except Exception as e:
                log.warning("pushgateway push failed: %s", e)
            await asyncio.sleep(interval)


async def run_metrics(args, *, ready_event=None) -> None:
    host, port = args.store.split(":")
    drt = await DistributedRuntime(store_host=host,
                                   store_port=int(port)).connect()
    agg = await ClusterMetricsAggregator(
        drt, args.namespace, args.component,
        scrape_interval=args.scrape_interval).start()
    runner = None
    pusher = None
    if args.push_url:
        pusher = asyncio.create_task(
            push_loop(agg, args.push_url, args.push_interval))
        log.info("metrics aggregator pushing to %s every %.1fs",
                 args.push_url, args.push_interval)
        print(f"metrics aggregator pushing to {args.push_url}", flush=True)
    else:
        runner = web.AppRunner(build_app(agg))
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", args.port)
        await site.start()
        log.info("metrics aggregator on :%d (ns=%s components=%s)",
                 args.port, args.namespace, args.component)
        print(f"metrics aggregator on :{args.port}", flush=True)
    if ready_event is not None:
        ready_event.set()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        if pusher is not None:
            pusher.cancel()
        await agg.stop()
        if runner is not None:
            await runner.cleanup()
        await drt.close()


def main(argv=None) -> None:
    ap = EnvDefaultsParser("dynamo-metrics")
    ap.add_argument("--store", default="127.0.0.1:4222")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", action="append", default=None,
                    help="worker component to scrape (repeatable)")
    ap.add_argument("--port", type=int, default=9091)
    ap.add_argument("--scrape-interval", type=float, default=1.0)
    ap.add_argument("--push-url", default=None,
                    help="pushgateway URL; set => push instead of serve")
    ap.add_argument("--push-interval", type=float, default=5.0)
    args = ap.parse_args(argv)
    if not args.component:
        args.component = ["backend"]
    from ..utils.logging_ext import init_logging
    init_logging()
    asyncio.run(run_metrics(args))


if __name__ == "__main__":
    main()
