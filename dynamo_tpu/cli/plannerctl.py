"""plannerctl: inspect and steer the running planner through the store.

    python -m dynamo_tpu.cli.plannerctl --store 127.0.0.1:4222 status
    python -m dynamo_tpu.cli.plannerctl decisions [--tail 20]
    python -m dynamo_tpu.cli.plannerctl override decode 4
    python -m dynamo_tpu.cli.plannerctl clear [decode]
    python -m dynamo_tpu.cli.plannerctl pause|resume

Overrides and pause are one JSON document at ``planner/{ns}/override``
(``{"paused": bool, "pools": {pool: replicas}}``) that the planner loop
watches live; ``status`` reads the lease-bound ``planner/{ns}/state`` key
(absent => no planner alive for that namespace).
"""

from __future__ import annotations

import asyncio
import json
import time

from ..planner.loop import decisions_prefix, override_key, state_key
from ..runtime.scale.shards import make_store_client
from ..utils.dynconfig import EnvDefaultsParser


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-plannerctl")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--namespace", default="dynamo")
    sub = p.add_subparsers(dest="action", required=True)
    sub.add_parser("status")
    dec = sub.add_parser("decisions")
    dec.add_argument("--tail", type=int, default=20)
    ov = sub.add_parser("override")
    ov.add_argument("pool")
    ov.add_argument("replicas", type=int)
    cl = sub.add_parser("clear")
    cl.add_argument("pool", nargs="?", default=None,
                    help="pool to clear (default: every override)")
    sub.add_parser("pause")
    sub.add_parser("resume")
    return p.parse_args(argv)


async def _load_override(store, ns: str) -> dict:
    raw = await store.get(override_key(ns))
    if not raw:
        return {"paused": False, "pools": {}}
    try:
        d = json.loads(raw.decode())
        return {"paused": bool(d.get("paused")),
                "pools": dict(d.get("pools") or {})}
    except (ValueError, json.JSONDecodeError):
        return {"paused": False, "pools": {}}


async def run(args) -> int:
    host, port = args.store.split(":")
    store = await make_store_client(host, int(port)).connect()
    ns = args.namespace
    try:
        if args.action == "status":
            raw = await store.get(state_key(ns))
            if not raw:
                print(f"no live planner for namespace {ns!r} "
                      f"(state key absent)")
                return 1
            st = json.loads(raw.decode())
            age = time.time() - st.get("ts", 0)
            mode = "DRY-RUN" if st.get("dry_run") else "live"
            flags = [mode, f"policy={st.get('policy')}",
                     f"connector={st.get('connector')}",
                     f"clamps={st.get('clamps')}",
                     f"signals={st.get('signal_source', 'flat')}"]
            if st.get("fleet"):
                flags.append("FLEET")
            if st.get("paused"):
                flags.append("PAUSED")
            print(f"planner[{ns}] {' '.join(flags)} "
                  f"(state {age:.1f}s old)")
            # fleet mode: per-model status records carry what the state
            # doc cannot (target, lifecycle state, chips)
            fstatus = {}
            if st.get("fleet"):
                from ..fleet.registry import fetch_fleet_status

                fstatus = await fetch_fleet_status(store, ns)
            for pool, d in sorted((st.get("pools") or {}).items()):
                ov = (st.get("overrides") or {}).get(pool)
                fs = fstatus.get(pool, {})
                fleet_cols = ""
                if fs:
                    fleet_cols = (f" state={fs.get('state')} "
                                  f"target={fs.get('target')} "
                                  f"chips={fs.get('chips')}")
                print(f"  {pool:<8} component={d.get('component')} "
                      f"replicas={d.get('replicas')} "
                      f"occupancy={d.get('occupancy')} "
                      f"queue={d.get('queue_depth')} "
                      f"kv={d.get('kv_utilization')} "
                      f"burn={d.get('slo_burn')} "
                      f"breaker_open={d.get('breaker_open')}"
                      + fleet_cols
                      + (f" OVERRIDE->{ov}" if ov is not None else ""))
            return 0
        if args.action == "decisions":
            items = await store.get_prefix(decisions_prefix(ns))
            items.sort(key=lambda kv: kv[0])
            for _key, value in items[-args.tail:]:
                try:
                    d = json.loads(value.decode())
                except (ValueError, json.JSONDecodeError):
                    continue
                sup = f" [{d['suppressed']}]" if d.get("suppressed") else ""
                dr = " (dry-run)" if d.get("dry_run") else ""
                print(f"#{d.get('seq'):>6} {d.get('pool'):<8} "
                      f"{d.get('action'):<10} {d.get('current')}->"
                      f"{d.get('target')}{sup}{dr}  {d.get('reason')}")
            return 0
        # mutations: read-modify-write the one override document
        ov = await _load_override(store, ns)
        if args.action == "override":
            ov["pools"][args.pool] = args.replicas
            print(f"override: {args.pool} -> {args.replicas} replicas")
        elif args.action == "clear":
            if args.pool is None:
                ov["pools"] = {}
                print("cleared every pool override")
            else:
                ov["pools"].pop(args.pool, None)
                print(f"cleared override for {args.pool}")
        elif args.action == "pause":
            ov["paused"] = True
            print("planner paused (decisions hold until resume)")
        elif args.action == "resume":
            ov["paused"] = False
            print("planner resumed")
        await store.put(override_key(ns), json.dumps(ov).encode())
        return 0
    finally:
        await store.close()


def main() -> None:
    raise SystemExit(asyncio.run(run(parse_args())))


if __name__ == "__main__":
    main()
