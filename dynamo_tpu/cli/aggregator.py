"""Regional aggregator daemon: one node of the hierarchical observer tree.

    python -m dynamo_tpu.cli.aggregator --store 127.0.0.1:4222 \
        [--namespace dynamo] [--interval 2.0]

Run N of these (any N; one is enough for thousands of workers, more
divide the merge work) against the same store. Each instance:

- registers lease-bound under ``regions/{ns}/{lease:x}`` — the lease id
  IS the region id, so a dead aggregator's record (and region) vanishes
  with its session;
- owns the rendezvous-hashed slice of the namespace's workers implied
  by the live aggregator set (it watches the ``regions/`` prefix for
  peers; membership churn only re-homes the affected region's workers);
- per ``--interval`` tick, pre-merges its workers' ``metrics_stage/``
  dumps (full+delta overlay) + ForwardPassMetrics snapshots and
  publishes ONE region record that the planner's signal collector, the
  SLO monitor, dyntop and ``fetch_stage_states`` read instead of the
  flat per-worker scrape.

Flags resolve env defaults as ``DYN_AGGREGATOR_<FLAG>`` (dynconfig
layering); ``--interval`` additionally honors ``DYN_REGION_INTERVAL``.
Zero aggregators running = every reader silently uses the flat scrape.
"""

from __future__ import annotations

import asyncio
import logging

from ..runtime.scale.regions import RegionalAggregator, region_interval
from ..utils.dynconfig import EnvDefaultsParser

log = logging.getLogger("dynamo_tpu.aggregator")


def parse_args(argv=None):
    p = EnvDefaultsParser(prog="dynamo-aggregator")
    p.add_argument("--store", default="127.0.0.1:4222")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--interval", type=float, default=None,
                   help="seconds between region merges (default: "
                        "DYN_REGION_INTERVAL, 2.0)")
    return p.parse_args(argv)


async def run_aggregator(args, *, ready_event=None,
                         drt=None) -> "RegionalAggregator":
    from ..llm.metrics_aggregator import StagePublisher
    from ..runtime.component import DistributedRuntime
    from ..utils import tracing

    own_drt = drt is None
    if own_drt:
        host, port = args.store.split(":")
        drt = await DistributedRuntime(store_host=host,
                                       store_port=int(port)).connect()
    tracing.configure(component="aggregator")
    # flight recorder + watchdog + incident coordination: a capture
    # beacon gets this aggregator's merge-loop view of the window too
    from .. import obs

    obs_handle = await obs.start_process(
        "aggregator", store=drt.store, namespace=args.namespace,
        proc_label=f"aggregator:{drt.worker_id:x}")
    interval = args.interval if args.interval is not None \
        else region_interval()
    agg = await RegionalAggregator(drt.store, args.namespace,
                                   agg_id=drt.worker_id, lease=drt.lease,
                                   interval=interval).start()
    # first record lands before "serving" prints, so a harness waiting
    # on the log line can immediately read a fresh region
    await agg.tick()
    agg.start_loop()
    # the aggregator's own dyn_region_merge_seconds histogram rides the
    # ordinary stage-metrics plane (delta-batched like any worker)
    publisher = StagePublisher(drt.store, args.namespace, "aggregator",
                               drt.worker_id, drt.lease)
    agg._drt = drt            # keeps the runtime alive with the daemon
    agg._own_drt = own_drt
    agg._obs_handle = obs_handle

    async def publish_loop():
        while True:
            try:
                await publisher.publish()
            except Exception:
                log.debug("aggregator stage publish skipped",
                          exc_info=True)
            await asyncio.sleep(max(interval, 1.0))

    from ..utils.aiotasks import spawn
    agg._pub_task = spawn(publish_loop(), name="aggregator-publish")
    print(f"regional aggregator serving (region={drt.worker_id:x}, "
          f"ns={args.namespace}, interval={interval}s)", flush=True)
    if ready_event is not None:
        ready_event.set()
    return agg


async def amain(args) -> None:
    agg = await run_aggregator(args)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await agg.stop()
        agg._pub_task.cancel()
        await agg._obs_handle.stop()
        if agg._own_drt:
            await agg._drt.close()


def main() -> None:
    from ..utils.logging_ext import init_logging

    init_logging()
    try:
        asyncio.run(amain(parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
