"""`serve`: run a @service graph as local processes.

    python -m dynamo_tpu.cli.serve examples.hello_world:Frontend \
        [--config examples/configs/hello.yaml] [--store host:port] \
        [--platform cpu|tpu] [--total-chips 4]

Reference capability: `dynamo serve` (deploy/dynamo/sdk/cli/serve.py +
serving.py local orchestration).
"""

from __future__ import annotations

import argparse
import logging
import signal
import time

from ..sdk.serve import LocalServe
from ..utils.dynconfig import EnvDefaultsParser

log = logging.getLogger("dynamo_tpu.cli.serve")


def load_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def main(argv=None) -> None:
    p = EnvDefaultsParser(prog="dynamo-serve")
    p.add_argument("entry", help="pkg.module:ServiceClass (graph entry)")
    p.add_argument("--config", default=None, help="per-service YAML")
    p.add_argument("--store", default=None,
                   help="existing dynstore host:port (default: spawn one)")
    p.add_argument("--platform", default="auto",
                   choices=["auto", "cpu", "tpu"])
    p.add_argument("--total-chips", type=int, default=4)
    args = p.parse_args(argv)

    from ..utils.logging_ext import init_logging
    init_logging()
    cfg = load_config(args.config) if args.config else {}
    serve = LocalServe(args.entry, config=cfg, store=args.store,
                       platform=args.platform, total_chips=args.total_chips)
    serve.start()
    print(f"serving {args.entry} (store {serve.store}); ctrl-c to stop",
          flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        serve.stop()


if __name__ == "__main__":
    main()
