"""Roofline accounting: how close is each dispatch to the hardware?

Three pieces, consumed by the engine's goodput telemetry
(``dyn_mfu`` / ``dyn_mbu`` / ``dyn_hbm_gbps``):

1. **Peaks** — per-platform peak dense bf16 FLOP/s and HBM bandwidth.
   TPU generations come from a static table (same figures bench.py has
   always used, plus memory bandwidth); off-chip (CPU) the peaks are
   *calibrated once* with a short matmul / memcpy measurement so MFU/MBU
   stay meaningful rather than reading 0.0001 against an imaginary chip.
   ``DYN_PEAK_FLOPS`` / ``DYN_PEAK_GBPS`` override everything (deployments
   that know their part better than the table).

2. **Analytic cost model** — FLOPs and HBM bytes of one engine dispatch,
   computed from the model config and the dispatch's actual lane lengths.
   Matmul FLOPs count dense projections + MLP (active experts only for
   MoE) + the LM head where the program really computes it; attention
   score/value FLOPs and KV reads are **window-clamped** on sliding-window
   layers (a Gemma-style 5:1 sliding stack reads a fraction of the KV a
   full-attention stack would). Bytes = weights streamed once per
   sequential step + KV read/written. Activations and padding lanes are
   deliberately excluded: the numbers are *useful* work, so bucket padding
   shows up as lost MFU instead of being flattered away.

3. :class:`GoodputMeter` — accumulates (flops, bytes, busy-time) per
   dispatch and answers with windowed MFU / MBU / achieved-GB/s rates plus
   lifetime totals (what bench.py stamps into its artifacts).

The model is an estimate, not a profiler: it exists so "are we 4% or 40%
of the chip" is answerable from /metrics on every deployment, and so the
bench artifacts can never again ship ``mfu: null``.
"""

from __future__ import annotations

import collections
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# device_kind substring -> (peak dense bf16 FLOP/s, peak HBM bytes/s) per
# chip — THE peak table (bench.py normalizes through here too); bandwidth
# from the public chip datasheets (v5e 819 GB/s, v5p 2765, v6e 1640,
# v4 1228).
PEAKS_BY_DEVICE_KIND: Tuple[Tuple[str, float, float], ...] = (
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v5lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
)


@dataclass(frozen=True)
class Peaks:
    """What the attached hardware could theoretically sustain."""

    flops: float          # dense bf16 FLOP/s
    hbm_bytes: float      # main-memory bytes/s
    source: str           # "table:<kind>" | "calibrated-cpu" | "env"


def _env_peaks() -> Optional[Peaks]:
    f = os.environ.get("DYN_PEAK_FLOPS")
    b = os.environ.get("DYN_PEAK_GBPS")
    if not (f and b):
        return None
    try:
        return Peaks(float(f), float(b) * 1e9, "env")
    except ValueError:
        return None


def _calibrate_cpu() -> Peaks:
    """Measure this host once: matmul FLOP/s (BLAS) and memcpy bandwidth.

    Deliberately short (~tens of ms): the point is a denominator within
    ~2x of the truth, so CPU MFU/MBU read as real percentages instead of
    noise against a TPU peak. Best-of-N to shave scheduler jitter."""
    import numpy as np

    n = 384
    a = np.random.default_rng(0).standard_normal((n, n), dtype=np.float32)
    b = np.ascontiguousarray(a.T)
    a @ b                                    # warm the BLAS threads
    flops = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        a @ b
        dt = time.perf_counter() - t0
        flops = max(flops, 2.0 * n * n * n / max(dt, 1e-9))
    src = np.zeros(32 << 20, dtype=np.uint8)  # 32 MiB: past typical LLC
    dst = np.empty_like(src)
    bw = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        # a copy moves 2x the buffer (read + write)
        bw = max(bw, 2.0 * src.nbytes / max(dt, 1e-9))
    return Peaks(flops, bw, "calibrated-cpu")


_CAL_CACHE: Dict[str, Peaks] = {}


def detect_peaks(device_kind: Optional[str] = None,
                 platform: Optional[str] = None) -> Peaks:
    """Peaks for the attached accelerator. ``device_kind``/``platform``
    default to jax's first device; passing them explicitly keeps this
    importable (and testable) without touching a backend."""
    env = _env_peaks()
    if env is not None:
        return env
    if device_kind is None or platform is None:
        import jax

        d = jax.devices()[0]
        device_kind, platform = d.device_kind, d.platform
    if platform not in ("cpu",):
        k = device_kind.lower()
        for sub, pf, pb in PEAKS_BY_DEVICE_KIND:
            if sub in k:
                return Peaks(pf, pb, f"table:{sub}")
    if "cpu" not in _CAL_CACHE:
        _CAL_CACHE["cpu"] = _calibrate_cpu()
    return _CAL_CACHE["cpu"]


# ---------------------------------------------------------------------------
# analytic dispatch cost model
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ModelCosts:
    """Per-config constants the dispatch cost functions combine.

    ``window_groups`` collapses the layer stack into ``(window, count)``
    groups — ``None`` = full attention — so the per-token clamped-length
    sum is O(distinct windows), not O(layers), on the engine hot path.
    All FLOP counts use 2 FLOPs per MAC."""

    mat_flops_per_token: float   # dense projections + (active-expert) MLP
    lm_head_flops: float         # 2 * D * V, charged where the head runs
    attn_flops_coef: float       # 4 * Hq * Dh: score+value FLOPs per kv pos
    kv_bytes_per_tok_layer: float  # 2 (k+v) * Hkv * Dh * esize
    num_layers: int
    window_groups: Tuple[Tuple[Optional[int], int], ...]
    weight_bytes: float          # total param bytes streamed per step


def dtype_size(dtype: Any) -> int:
    import numpy as np

    try:
        import jax.numpy as jnp

        return int(np.dtype(jnp.zeros((), dtype).dtype).itemsize)
    # dynalint: ok(swallowed-exception) jax-dtype probe falling back to
    # the numpy interpretation IS the handling; both paths return a size
    except Exception:
        return int(np.dtype(dtype).itemsize)


def model_costs(m: Any, weight_bytes: Optional[float] = None) -> ModelCosts:
    """Build :class:`ModelCosts` from a ``LlamaConfig``-shaped object.
    ``weight_bytes`` overrides the analytic parameter count with the exact
    loaded size when the caller has it (the engine does)."""
    D, V = m.hidden_size, m.vocab_size
    Hq, Hkv, Dh = m.num_heads, m.num_kv_heads, m.head_dim
    L, I = m.num_layers, m.intermediate_size
    esize = dtype_size(m.dtype)
    attn_proj = D * Hq * Dh + 2 * D * Hkv * Dh + Hq * Dh * D
    if getattr(m, "num_experts", 0):
        mlp_active = m.experts_per_token * 3 * D * I
        mlp_weights = m.num_experts * 3 * D * I
    else:
        mlp_active = mlp_weights = 3 * D * I
    if weight_bytes is None:
        n_params = V * D + L * (attn_proj + mlp_weights)
        if not getattr(m, "tie_embeddings", False):
            n_params += D * V
        weight_bytes = float(n_params) * esize
    groups: Dict[Optional[int], int] = {}
    for layer in range(L):
        w = m.sliding_window if m.layer_sliding(layer) else None
        groups[w] = groups.get(w, 0) + 1
    return ModelCosts(
        mat_flops_per_token=2.0 * L * (attn_proj + mlp_active),
        lm_head_flops=2.0 * D * V,
        attn_flops_coef=4.0 * Hq * Dh,
        kv_bytes_per_tok_layer=2.0 * Hkv * Dh * esize,
        num_layers=L,
        window_groups=tuple(sorted(groups.items(),
                                   key=lambda kv: (kv[0] is None, kv[0]))),
        weight_bytes=float(weight_bytes),
    )


def _clamped_len_sum(groups: Sequence[Tuple[Optional[int], int]],
                     s: int) -> float:
    """sum over layers of min(s, window): the kv positions one query token
    at kv-length ``s`` actually touches across the layer stack."""
    return float(sum((min(s, w) if w is not None else s) * n
                     for w, n in groups))


def decode_cost(c: ModelCosts, lengths: Iterable[int], steps: int
                ) -> Tuple[float, float, int]:
    """(flops, bytes, tokens) of a multi-step decode dispatch: ``steps``
    scan iterations over the given per-lane kv lengths (active lanes only).
    Weights stream once per scan step; every token computes the LM head."""
    flops = 0.0
    kv_read = 0.0
    lanes = 0
    for s0 in lengths:
        lanes += 1
        for j in range(steps):
            touched = _clamped_len_sum(c.window_groups, s0 + j)
            flops += (c.mat_flops_per_token + c.lm_head_flops
                      + c.attn_flops_coef * touched)
            kv_read += touched * c.kv_bytes_per_tok_layer
    tokens = lanes * steps
    bytes_ = (steps * c.weight_bytes + kv_read
              + tokens * c.num_layers * c.kv_bytes_per_tok_layer)
    return flops, bytes_, tokens


def prefill_cost(c: ModelCosts, spans: Iterable[Tuple[int, int]]
                 ) -> Tuple[float, float, int]:
    """(flops, bytes, tokens) of one batched prefill dispatch over
    ``(start, count)`` prompt spans (per active lane). The program computes
    the LM head once per lane (at ``logits_idx``) regardless of whether the
    host keeps the sample, so it is charged once per lane."""
    flops = 0.0
    kv_read = 0.0
    tokens = 0
    for start, count in spans:
        tokens += count
        flops += count * c.mat_flops_per_token + c.lm_head_flops
        for p in range(start, start + count):
            touched = _clamped_len_sum(c.window_groups, p + 1)
            flops += c.attn_flops_coef * touched
            kv_read += touched * c.kv_bytes_per_tok_layer
    bytes_ = (c.weight_bytes + kv_read
              + tokens * c.num_layers * c.kv_bytes_per_tok_layer)
    return flops, bytes_, tokens


def verify_cost(c: ModelCosts, lengths: Iterable[int], t: int
                ) -> Tuple[float, float, int]:
    """(flops, bytes, tokens) of a speculative verify dispatch: ONE forward
    over ``t = k+1`` positions per active lane, LM head at every position
    (the verify sampler consumes all of them)."""
    flops = 0.0
    kv_read = 0.0
    lanes = 0
    for s0 in lengths:
        lanes += 1
        for j in range(t):
            touched = _clamped_len_sum(c.window_groups, s0 + j)
            flops += (c.mat_flops_per_token + c.lm_head_flops
                      + c.attn_flops_coef * touched)
            kv_read += touched * c.kv_bytes_per_tok_layer
    tokens = lanes * t
    bytes_ = (c.weight_bytes + kv_read
              + tokens * c.num_layers * c.kv_bytes_per_tok_layer)
    return flops, bytes_, tokens


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------
class GoodputMeter:
    """Accumulates dispatch costs and answers utilization questions.

    ``account()`` is called once per *measured* dispatch (dispatch-to-host-
    results wall time; pipelined decode deliberately overlaps, same as the
    ``llm_decode_step_seconds`` convention). ``snapshot()`` rates over a
    sliding window of recent dispatches — what the live gauges and
    ForwardPassMetrics export; ``lifetime()`` over every accounted dispatch
    — what bench artifacts record. First-call-per-program compile time must
    NOT be accounted here (the engine routes it to the compile counters
    instead), or one XLA compile would crater the window's MFU."""

    def __init__(self, costs: ModelCosts, peaks: Peaks,
                 window_s: float = 10.0):
        import threading

        self.costs = costs
        self.peaks = peaks
        self.window_s = window_s
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.busy_s_total = 0.0
        self.tokens_total = 0
        self.dispatches = 0
        self._recent: collections.deque = collections.deque()
        # account() runs on the engine thread; snapshot()/lifetime() on the
        # asyncio metrics loop — iterating the deque mid-append raises and
        # would kill the caller's loop, so every touch takes this lock
        self._lock = threading.Lock()

    def account(self, flops: float, bytes_: float, elapsed_s: float,
                tokens: int = 0) -> None:
        if elapsed_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self.flops_total += flops
            self.bytes_total += bytes_
            self.busy_s_total += elapsed_s
            self.tokens_total += tokens
            self.dispatches += 1
            self._recent.append((now, flops, bytes_, elapsed_s))
            cutoff = now - self.window_s
            while self._recent and self._recent[0][0] < cutoff:
                self._recent.popleft()

    def _rates(self, flops: float, bytes_: float, busy: float
               ) -> Dict[str, float]:
        if busy <= 0:
            return {"mfu": 0.0, "mbu": 0.0, "hbm_gbps": 0.0}
        return {
            "mfu": flops / busy / self.peaks.flops,
            "mbu": bytes_ / busy / self.peaks.hbm_bytes,
            "hbm_gbps": bytes_ / busy / 1e9,
        }

    def snapshot(self) -> Dict[str, float]:
        """MFU/MBU/GB/s over the recent window (0.0 when idle)."""
        cutoff = time.monotonic() - self.window_s
        f = b = t = 0.0
        with self._lock:
            recent = list(self._recent)
        for ts, fl, by, el in recent:
            if ts >= cutoff:
                f += fl
                b += by
                t += el
        return self._rates(f, b, t)

    def lifetime(self) -> Dict[str, float]:
        """Cumulative utilization over every accounted dispatch, plus the
        raw totals (bench artifacts embed these)."""
        with self._lock:
            totals = (self.flops_total, self.bytes_total, self.busy_s_total,
                      self.tokens_total, self.dispatches)
        out = self._rates(totals[0], totals[1], totals[2])
        out.update(flops_total=totals[0],
                   bytes_total=totals[1],
                   busy_s=totals[2],
                   tokens=float(totals[3]),
                   dispatches=float(totals[4]),
                   peak_flops=self.peaks.flops,
                   peak_hbm_gbps=self.peaks.hbm_bytes / 1e9,
                   peak_source=self.peaks.source)
        return out


def record_compile(kind: str, seconds: float) -> None:
    """Fold one program build into the process compile-plane counters
    (``dyn_compile_seconds_total`` / ``dyn_compiled_programs{kind}``)."""
    from .prometheus import stage_metrics

    sm = stage_metrics()
    sm.compile_seconds.inc(kind, amount=seconds)
    sm.compiled_programs.inc(kind)


def instrument_compile(kind: str, fn: Callable,
                       on_compile: Callable[[str, float], None]) -> Callable:
    """Wrap a freshly-built jitted program so its FIRST call — the one that
    traces and XLA-compiles synchronously before launching — is timed and
    reported via ``on_compile(kind, seconds)``. Later calls pass through
    untouched. This is how ``dyn_compile_seconds_total`` /
    ``dyn_compiled_programs`` see warmup AND mid-serving bucket compiles
    without instrumenting every dispatch site."""
    state = {"first": True}

    def wrapper(*args, **kwargs):
        if state["first"]:
            state["first"] = False
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            on_compile(kind, time.perf_counter() - t0)
            return out
        return fn(*args, **kwargs)

    return wrapper
