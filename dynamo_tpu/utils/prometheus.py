"""Minimal Prometheus text-format metrics (no client library in the image).

Counters, gauges and histograms with labels, rendered in exposition format at
``/metrics``. Reference capability: lib/llm/src/http/service/metrics.rs and
components/metrics prometheus export.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0
)

# Per-metric bucket presets: the shared default starts at 5 ms, which
# collapses ms-scale signals (inter-token latency, decode step) into the
# first bucket. FAST resolves 200 µs – 1 s; WIDE resolves 10 ms – 2 min
# (TTFT, queue wait, KV transfer over DCN).
LATENCY_BUCKETS_FAST = (
    0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0,
)
LATENCY_BUCKETS_WIDE = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    120.0,
)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help_: str, labels: Sequence[str]):
        self.name = name
        self.help = help_
        self.labels = tuple(labels)
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, labels=()):
        super().__init__(name, help_, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, *label_values: str) -> float:
        key = tuple(str(v) for v in label_values)
        with self._lock:   # a torn read would race concurrent inc()
            return self._values.get(key, 0.0)

    def clear_label(self, pos: int, value: str) -> None:
        """Drop every series whose label at ``pos`` equals ``value`` (e.g.
        re-exporting a component's worker set after a scrape: dead workers'
        series must vanish rather than freeze at their last value)."""
        v = str(value)
        with self._lock:
            for key in [k for k in self._values if k[pos] == v]:
                del self._values[key]

    def render(self) -> List[str]:
        with self._lock:   # snapshot: render must not race inc/set
            items = sorted(self._values.items())
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(self.labels, key)} {v}")
        return out

    def state(self) -> Dict[str, Any]:
        """JSON-serializable snapshot (cross-process metric aggregation)."""
        with self._lock:
            series = {"\x1f".join(k): v for k, v in self._values.items()}
        return {"kind": self.kind, "help": self.help,
                "labels": list(self.labels), "series": series}


class Gauge(Counter):
    kind = "gauge"

    def set(self, *label_values: str, value: float) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = value

    def dec(self, *label_values: str, amount: float = 1.0) -> None:
        self.inc(*label_values, amount=-amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, labels=(), buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, *label_values: str, value: float) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            # per-bucket (non-cumulative) storage: render() cumulates.
            # (Incrementing every bucket >= value here double-counted once
            # render summed again — le= lines used to overshoot.)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def get_count(self, *label_values: str) -> int:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            return self._totals.get(key, 0)

    def render(self) -> List[str]:
        with self._lock:   # snapshot: render must not race observe()
            items = sorted((k, list(c)) for k, c in self._counts.items())
            sums = dict(self._sums)
            totals = dict(self._totals)
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for key, counts in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lbls = _fmt_labels(self.labels + ("le",), key + (repr(b).rstrip("0").rstrip("."),))
                out.append(f"{self.name}_bucket{lbls} {cum}")
            lbls_inf = _fmt_labels(self.labels + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lbls_inf} {totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.labels, key)} {sums[key]}")
            out.append(f"{self.name}_count{_fmt_labels(self.labels, key)} {totals[key]}")
        return out

    def state(self) -> Dict[str, Any]:
        with self._lock:
            series = {
                "\x1f".join(k): {"counts": list(c),
                                 "sum": self._sums.get(k, 0.0),
                                 "total": self._totals.get(k, 0)}
                for k, c in self._counts.items()}
        return {"kind": self.kind, "help": self.help,
                "labels": list(self.labels), "buckets": list(self.buckets),
                "series": series}


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []

    def counter(self, name, help_, labels=()) -> Counter:
        m = Counter(name, help_, labels)
        self._metrics.append(m)
        return m

    def gauge(self, name, help_, labels=()) -> Gauge:
        m = Gauge(name, help_, labels)
        self._metrics.append(m)
        return m

    def histogram(self, name, help_, labels=(), buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_, labels, buckets)
        self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def state_dump(self) -> Dict[str, Dict]:
        """Snapshot every metric's state — the unit workers publish to the
        store so a cluster scraper can merge histograms across processes."""
        return {m.name: m.state() for m in self._metrics}


def diff_states(base: Dict[str, Dict], cur: Dict[str, Dict],
                ignore: Sequence[str] = ()) -> Dict[str, Dict]:
    """The metrics of ``cur`` whose state changed vs ``base`` — the
    coalesced **delta batch** a worker publishes between full snapshots.

    Granularity is the whole metric (a changed metric ships all its
    series), so applying a delta onto the full image it was diffed
    against is a plain dict overlay — no per-series merge semantics to
    get wrong across process restarts. ``ignore`` names metrics excluded
    from change detection (the publisher's own push counters would
    otherwise make every interval a delta)."""
    skip = set(ignore)
    return {name: st for name, st in cur.items()
            if name not in skip and base.get(name) != st}


#: gauges whose series describe a STATE (enum / worst-of), not a quantity:
#: merging across publishers must take the max, never the sum — summing two
#: observers' OPEN(2) circuit states would read as 4 and match no state
GAUGE_MERGE_MAX = frozenset({"dyn_circuit_state", "dyn_brownout_level"})


def merge_state_dumps(dumps: Iterable[Dict[str, Dict]],
                      gauge_max: Iterable[str] = GAUGE_MERGE_MAX
                      ) -> Dict[str, Dict]:
    """Reduce many ``registry.state_dump()`` images into ONE equivalent
    dump — the regional aggregator's pre-merge (runtime/scale/regions.py).

    Merge rules match what every state-dump consumer already assumes:
    counters and histogram counts/sums/totals add (so quantile/burn/total
    math over the merged dump equals the same math over the originals);
    gauges add too — per-worker gauges carry a worker/observer label, so
    addition is concatenation — EXCEPT the state-enum gauges in
    ``gauge_max``, which take the worst value. Metrics with mismatched
    kind/labels/buckets across dumps keep the first image seen (same
    skip-don't-corrupt rule as :func:`render_states`)."""
    gauge_max = set(gauge_max)
    out: Dict[str, Dict] = {}
    for dump in dumps:
        for name, st in dump.items():
            if not isinstance(st, dict):
                continue
            cur = out.get(name)
            if cur is None:
                # deep-copy histogram series: the merge accumulates in
                # place and must never mutate a caller's dump
                series0 = {
                    k: ({"counts": list(v.get("counts") or ()),
                         "sum": v.get("sum", 0.0),
                         "total": v.get("total", 0)}
                        if st.get("kind") == "histogram" else v)
                    for k, v in (st.get("series") or {}).items()}
                out[name] = {**st, "series": series0}
                continue
            if (cur.get("kind") != st.get("kind")
                    or list(cur.get("labels") or ()) != list(
                        st.get("labels") or ())):
                continue
            kind = st.get("kind")
            if kind == "histogram" and list(st.get("buckets") or ()) != \
                    list(cur.get("buckets") or ()):
                continue
            series = cur["series"]
            for skey, val in (st.get("series") or {}).items():
                prev = series.get(skey)
                if prev is None:
                    series[skey] = ({"counts": list(val["counts"]),
                                     "sum": val["sum"],
                                     "total": val["total"]}
                                    if kind == "histogram" else val)
                elif kind == "histogram":
                    if len(prev.get("counts") or ()) == len(
                            val.get("counts") or ()):
                        prev["counts"] = [a + b for a, b in zip(
                            prev["counts"], val["counts"])]
                        prev["sum"] += val["sum"]
                        prev["total"] += val["total"]
                elif kind == "counter":
                    series[skey] = prev + val
                elif name in gauge_max:
                    series[skey] = max(prev, val)
                else:
                    series[skey] = prev + val
    return out


def hist_quantile(buckets, counts, total, q: float) -> Optional[float]:
    """Bucket upper edge covering quantile ``q`` of a state-dump
    histogram (conservative: the true value is <= the returned edge).
    ``inf`` when the quantile falls in the overflow bucket, ``None`` on
    an empty histogram. The shared bucket-walk for every consumer of
    ``state_dump()`` histograms (dyntop's store line, the fleet-soak
    scaling curve)."""
    if not total:
        return None
    target = q * total
    cum = 0
    for edge, c in zip(buckets or (), counts or ()):
        cum += c
        if cum >= target:
            return float(edge)
    return float("inf")


# ---------------------------------------------------------------------------
# cross-process merge + render of state dumps
# ---------------------------------------------------------------------------
def render_states(states: Iterable[Tuple[str, Dict[str, Dict]]]) -> str:
    """Render ``(component, registry.state_dump())`` pairs as one exposition
    block, each series tagged with a leading ``component`` label. Series from
    multiple processes of the SAME component merge: counters/histogram counts
    sum, gauges last-write-wins (per-worker gauges should carry a worker
    label instead of relying on this)."""
    # metric name -> (kind, help, labels, buckets, {(component,)+key -> val})
    merged: Dict[str, Dict[str, Any]] = {}
    for component, dump in states:
        for name, st in dump.items():
            m = merged.setdefault(name, {
                "kind": st["kind"], "help": st.get("help", ""),
                "labels": list(st.get("labels", ())),
                "buckets": st.get("buckets"), "series": {}})
            if m["kind"] != st["kind"] or m["labels"] != list(
                    st.get("labels", ())):
                continue    # incompatible foreign dump: skip, don't corrupt
            if (st["kind"] == "histogram"
                    and list(st.get("buckets") or ()) != list(
                        m["buckets"] or ())):
                continue    # different bucket layout (mixed-version
                            # rollout): summing or relabelling would lie
            for skey, val in st.get("series", {}).items():
                key = (component,) + tuple(skey.split("\x1f")) \
                    if skey else (component,)
                cur = m["series"].get(key)
                if st["kind"] == "histogram":
                    if (cur is not None and m["buckets"] is not None
                            and len(cur["counts"]) == len(val["counts"])):
                        cur["counts"] = [a + b for a, b in
                                         zip(cur["counts"], val["counts"])]
                        cur["sum"] += val["sum"]
                        cur["total"] += val["total"]
                    else:
                        m["series"][key] = {"counts": list(val["counts"]),
                                            "sum": val["sum"],
                                            "total": val["total"]}
                elif st["kind"] == "counter":
                    m["series"][key] = (cur or 0.0) + val
                else:   # gauge
                    m["series"][key] = val
    lines: List[str] = []
    for name, m in sorted(merged.items()):
        labels = ("component",) + tuple(m["labels"])
        lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['kind']}")
        for key, val in sorted(m["series"].items()):
            if m["kind"] == "histogram":
                cum = 0
                for b, c in zip(m["buckets"] or (), val["counts"]):
                    cum += c
                    lb = _fmt_labels(labels + ("le",),
                                     key + (repr(b).rstrip("0").rstrip("."),))
                    lines.append(f"{name}_bucket{lb} {cum}")
                lines.append(f"{name}_bucket"
                             f"{_fmt_labels(labels + ('le',), key + ('+Inf',))}"
                             f" {val['total']}")
                lines.append(f"{name}_sum{_fmt_labels(labels, key)}"
                             f" {val['sum']}")
                lines.append(f"{name}_count{_fmt_labels(labels, key)}"
                             f" {val['total']}")
            else:
                lines.append(f"{name}{_fmt_labels(labels, key)} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# per-stage LLM latency metrics (one set per process, own registry)
# ---------------------------------------------------------------------------
class StageMetrics:
    """The request-lifecycle flight-recorder histograms every serving
    process records locally: TTFT, inter-token latency, prefill queue wait,
    KV-transfer duration/bytes, decode step time, batch occupancy. Workers
    publish ``registry.state_dump()`` to the store; the metrics aggregator
    and the HTTP frontend's ``/metrics`` merge them cluster-wide via
    :func:`render_states`."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.ttft = r.histogram(
            "llm_ttft_seconds", "Time to first token", ("model",),
            buckets=LATENCY_BUCKETS_WIDE)
        self.inter_token = r.histogram(
            "llm_inter_token_seconds", "Gap between streamed tokens",
            ("model",), buckets=LATENCY_BUCKETS_FAST)
        self.queue_wait = r.histogram(
            "llm_prefill_queue_wait_seconds",
            "Remote prefill job wait in the shared queue", (),
            buckets=LATENCY_BUCKETS_WIDE)
        self.kv_transfer = r.histogram(
            "llm_kv_transfer_seconds",
            "Prefill->decode KV block transfer duration", ("direction",),
            # sub-ms on loopback, seconds over DCN: fast floor, coarse tail
            buckets=LATENCY_BUCKETS_FAST + (2.5, 10.0, 60.0))
        self.kv_transfer_bytes = r.counter(
            "llm_kv_transfer_bytes_total",
            "Bytes of KV moved prefill->decode", ("direction",))
        self.decode_step = r.histogram(
            "llm_decode_step_seconds", "One engine decode iteration", (),
            buckets=LATENCY_BUCKETS_FAST)
        self.batch_occupancy = r.gauge(
            "llm_batch_occupancy", "Active sequences in the engine batch",
            # per-worker label (pid): render_states merges same-component
            # gauges last-write-wins, which would collapse replicas
            ("worker",))
        # robustness plane (store reconnect / deadlines / circuit breaker):
        # counted here so they ride the existing publish_stage_metrics →
        # aggregator merge path with zero new plumbing
        self.store_reconnects = r.counter(
            "dyn_store_reconnects_total",
            "Store reconnect outcomes", ("result",))   # attempt|ok|fail
        self.lease_regrants = r.counter(
            "dyn_lease_regrants_total",
            "Leases re-granted after a store reconnect", ())
        self.session_replays = r.counter(
            "dyn_session_replay_total",
            "Session state replayed on reconnect", ("kind",))
        self.deadline_expiries = r.counter(
            "dyn_deadline_expiries_total",
            "Requests expired at a pipeline stage", ("stage",))
        self.circuit_state = r.gauge(
            "dyn_circuit_state",
            "Per-instance circuit breaker state "
            "(0=closed 1=half-open 2=open)",
            # observer label (pid): each client process has its OWN view of
            # an instance's circuit; merging them last-write-wins would
            # make the series flap between observers' states
            ("observer", "instance"))
        self.faults_injected = r.counter(
            "dyn_faults_injected_total",
            "Fault-injection points fired", ("point", "action"))
        # speculative decoding (engine/spec.py): proposal/acceptance volume
        # plus the accepted-per-dispatch shape — the two numbers that tell
        # an operator whether spec decode is paying for its verify passes
        self.spec_proposed = r.counter(
            "dyn_spec_proposed_total",
            "Draft tokens proposed for speculative verification", ())
        self.spec_accepted = r.counter(
            "dyn_spec_accepted_total",
            "Draft tokens accepted by speculative verification", ())
        self.spec_per_dispatch = r.histogram(
            "dyn_spec_accepted_per_dispatch",
            "Accepted draft tokens per verify dispatch (per lane)", (),
            # token counts, not latencies: one bucket per plausible k
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
        # goodput plane (utils/roofline.py): analytic FLOPs/bytes per
        # dispatch over measured dispatch wall time against the platform
        # peak table — "how close to the hardware is this worker"
        self.mfu = r.gauge(
            "dyn_mfu", "Model FLOP utilization over the recent dispatch "
            "window (analytic cost model / platform peak)", ("worker",))
        self.mbu = r.gauge(
            "dyn_mbu", "Memory bandwidth utilization over the recent "
            "dispatch window", ("worker",))
        self.hbm_gbps = r.gauge(
            "dyn_hbm_gbps", "Achieved main-memory GB/s over the recent "
            "dispatch window", ("worker",))
        # compile plane: warmup cost and bucket-explosion regressions are
        # invisible in latency histograms until they hit a request — count
        # every XLA program build (first call of a fresh bucket program)
        self.compile_seconds = r.counter(
            "dyn_compile_seconds_total",
            "Wall seconds spent XLA-compiling bucket programs", ("kind",))
        self.compiled_programs = r.counter(
            "dyn_compiled_programs",
            "Bucket programs compiled", ("kind",))   # prefill|decode|verify|draft
        # model-mobility plane (fleet/mobility/): weight prefetch + hot
        # swap — a swap that recompiles or silently reloads cold defeats
        # the seconds-scale wake contract, so both are first-class series
        self.weight_cache_bytes = r.gauge(
            "dyn_weight_cache_bytes",
            "Host-RAM weight-cache residency by pin state "
            "(LRU budget: DYN_WEIGHT_CACHE_BYTES)", ("state",))
        self.model_swaps = r.counter(
            "dyn_model_swaps_total",
            "Model swap attempts by outcome (swap = in-place, reload = "
            "typed full-reload fallback)",
            ("outcome",))   # swap|reload|shape_mismatch|error
        self.model_wake_seconds = r.histogram(
            "dyn_model_wake_seconds",
            "Model wake latency from swap command (or spawn) to serving "
            "registration, by wake path", ("path",),   # swap|cold
            buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 45.0, 90.0, 180.0))
        # SLO burn rates (utils/slo.py): whoever runs an SloMonitor in this
        # process exports through here and the stage-metrics merge path
        self.slo_burn = r.gauge(
            "dyn_slo_burn_rate",
            "Error-budget burn rate per SLO and window (1.0 = budget "
            "consumed exactly at the sustainable rate)", ("slo", "window"))
        # overload-control plane (utils/overload.py): sheds are the
        # goodput-preserving outcome under pressure — they must be as
        # visible as the failures they replace
        self.admission_rejects = r.counter(
            "dyn_admission_rejects_total",
            "Requests rejected at HTTP admission (immediate 429)",
            ("reason", "priority"))   # rate_limit|concurrency|brownout...
        self.queue_shed = r.counter(
            "dyn_queue_shed_total",
            "Requests shed at a bounded stage queue (depth bound or "
            "predicted-late)", ("stage",))
        self.brownout_level = r.gauge(
            "dyn_brownout_level",
            "Active brownout degradation level (0=normal 1=shed-batch "
            "2=cap-tokens 3=no-spec 4=shed-all)", ())
        self.admission_depth = r.gauge(
            "dyn_admission_queue_depth",
            "In-flight requests currently held by the admission "
            "controller", ())
        self.admission_kv_bytes = r.gauge(
            "dyn_admission_kv_bytes",
            "Estimated KV bytes of all admitted in-flight requests (the "
            "byte-honest admission dimension; 0 when DYN_ADMIT_KV_BYTES "
            "is off)", ())
        # tenancy plane (utils/overload.py TenantAdmission/BurnTracker):
        # quota sheds are deliberate isolation, counted separately from
        # overload sheds so rejected-demand autoscaling pressure stays
        # blind to them; label cardinality is bounded to the quota table
        # plus "other" (tenant ids are client-controlled strings)
        self.tenant_rejects = r.counter(
            "dyn_tenant_admission_rejects_total",
            "Requests rejected by a per-tenant quota at HTTP ingress "
            "(tenant_rate | tenant_concurrency)", ("tenant", "reason"))
        self.tenant_requests = r.counter(
            "dyn_tenant_requests_total",
            "HTTP requests by tenant and status (the per-tenant "
            "availability burn's input)", ("tenant", "status"))
        self.tenant_inflight = r.gauge(
            "dyn_tenant_inflight",
            "In-flight requests per quota-governed tenant", ("tenant",))
        self.tenant_burn = r.gauge(
            "dyn_tenant_slo_burn",
            "Per-tenant availability error-budget burn, worst window "
            "(feeds the brownout ladder when DYN_TENANT_AVAILABILITY is "
            "set)", ("tenant",))
        # fleet-safe telemetry pipelines (utils/tracing.py head sampling +
        # the span sink's bounded retain-on-outage buffer, and the stage
        # publisher's delta batching): the pressure-relief valves must be
        # as observable as the planes they protect
        self.spans_sampled_out = r.counter(
            "dyn_spans_sampled_out_total",
            "Finished spans withheld from the store sink by trace-id "
            "head sampling (DYN_TRACE_SAMPLE); error traces are never "
            "sampled away", ())
        self.spans_dropped = r.counter(
            "dyn_spans_dropped_total",
            "Spans evicted from the span sink's bounded retain-on-outage "
            "buffer (oldest first) — nonzero means a store outage "
            "outlasted the buffer", ())
        self.metrics_pushes = r.counter(
            "dyn_metrics_pushes_total",
            "Stage-metrics publishes by kind: full snapshot, coalesced "
            "delta, or skipped (nothing changed — no store write)",
            ("kind",))   # full|delta|skipped
        self.stage_service = r.histogram(
            "dyn_stage_service_seconds",
            "Observed per-item service time of a bounded stage (the "
            "predictive shed's wait estimate input)", ("stage",),
            buckets=LATENCY_BUCKETS_FAST + (2.5, 10.0, 60.0))
        # KV tier + cluster-sharing plane (llm/kvbm/tiers.py and
        # llm/kv_cluster/): host/disk tier effectiveness was previously a
        # dict nobody scraped; cluster sharing makes the tiers a fleet
        # resource, so their hit economics must be first-class series
        self.kv_tier_hits = r.counter(
            "dyn_kv_tier_hits_total",
            "KV tier lookups served from a tier (admission restores and "
            "disk promotions)", ("tier",))   # host|disk
        self.kv_tier_misses = r.counter(
            "dyn_kv_tier_misses_total",
            "KV tier lookups that missed every local tier", ())
        self.kv_tier_blocks = r.gauge(
            "dyn_kv_tier_blocks",
            "Sealed KV blocks resident per tier", ("tier", "worker"))
        self.kv_cluster_hits = r.counter(
            "dyn_kv_cluster_hits_total",
            "Routed requests whose cluster-registry match exceeded the "
            "chosen worker's local overlap (a donor was stamped)", ())
        self.kv_cluster_fetches = r.counter(
            "dyn_kv_cluster_fetches_total",
            "Peer prefix fetches that deposited blocks into the local "
            "host tier", ())
        self.kv_cluster_fallbacks = r.counter(
            "dyn_kv_cluster_fallbacks_total",
            "Cluster fetches abandoned (timeout / donor death / error) — "
            "the request fell back to local prefill recompute", ())
        self.kv_cluster_fetch_seconds = r.histogram(
            "dyn_kv_cluster_fetch_seconds",
            "Peer prefix fetch duration, request out to blocks deposited",
            (), buckets=LATENCY_BUCKETS_FAST + (2.5, 10.0))
        # mid-stream failover (llm/resume.py): a broken stream re-enters
        # the router under the same context id and a new worker continues
        # from the next token — the client sees a pause, not a 503
        self.stream_resumes = r.counter(
            "dyn_stream_resumes_total",
            "Mid-stream failover attempts by outcome: resumed (a new "
            "worker continued the stream), exhausted (DYN_RESUME_MAX "
            "spent -> typed 503 resume_exhausted), expired (original "
            "deadline passed mid-retry -> 504)", ("outcome",))
        self.resume_kv_reattach_blocks = r.counter(
            "dyn_resume_kv_reattach_blocks_total",
            "Sealed KV blocks a resumed request re-attached at admission "
            "(cluster-fetched or tier-restored) instead of re-prefilling "
            "— zero on a resume means the full-local-prefill fallback "
            "path was taken", ())
        self.resume_latency = r.histogram(
            "dyn_resume_latency_seconds",
            "Client-visible pause per successful resume: stream break "
            "detected to first frame from the replacement worker",
            (), buckets=LATENCY_BUCKETS_FAST + (2.5, 10.0))
        # layer-streamed KV ingestion (llm/kv_transfer.py streamed mode):
        # each arriving layer's device scatter is enqueued while later
        # layers are still in flight; a torn stream (donor death, codec
        # violation, abandoned waiter) degrades to counted local prefill
        # with the partially-written pool pages released unseen
        self.kv_stream_ingests = r.counter(
            "dyn_kv_stream_ingests_total",
            "Remote-prefill KV streams ingested layer-by-layer into the "
            "decode pool (scatters overlapped with arrival)", ())
        self.kv_stream_fallbacks = r.counter(
            "dyn_kv_stream_fallbacks_total",
            "Streamed KV ingests aborted mid-stream (torn transfer / "
            "codec violation / abandoned waiter) — pool pages released, "
            "request fell back to local prefill", ("reason",))
        # per-(src,dst)-pair KV transfer bandwidth: EWMA observed by the
        # RECEIVER of every disagg push / cluster fetch — the
        # TransferCostModel's pair-aware input (src "q" = unknown sender,
        # e.g. the anonymous prefill-worker pool)
        self.kv_pair_bw = r.gauge(
            "llm_kv_pair_bw_bytes_per_s",
            "Observed KV transfer bandwidth per (src,dst) worker pair, "
            "exponentially weighted", ("src", "dst"))
        # placement-driven h2d prefetch (engine/engine.py stage_prefetch):
        # matched host/disk-tier prefix blocks uploaded to a device
        # staging buffer while the request still waits in the slot-gate
        # queue, consumed by admission's restore as a d2d scatter
        self.prefetch_h2d_hits = r.counter(
            "dyn_prefetch_h2d_hits_total",
            "Tier-resident prefix blocks admission restored from the "
            "prefetched device staging buffer (no h2d on the critical "
            "path)", ())
        self.prefetch_h2d_stalls = r.counter(
            "dyn_prefetch_h2d_stalls_total",
            "Tier-resident prefix blocks admission had to upload "
            "synchronously although a prefetch had been requested "
            "(prefetch incomplete or staging evicted)", ())
        # KV paging plane (llm/kvpage/): the virtual-memory counters —
        # demotions (d2h seal-and-demote), page-ins (async staged h2d),
        # faults (synchronous inline page-ins: the number that must stay
        # at zero in steady-state decode), and the lane's true footprint
        # in bytes (device-resident pages + pinned host working set)
        self.kvpage_demotions = r.counter(
            "dyn_kvpage_demotions_total",
            "KV blocks sealed and demoted d2h to the host tier by the "
            "paging plane", ())
        self.kvpage_pageins = r.counter(
            "dyn_kvpage_pageins_total",
            "Cold-block segments paged in h2d ahead of the attention "
            "pass that read them (async prefetch hits)", ())
        self.kvpage_faults = r.counter(
            "dyn_kvpage_faults_total",
            "Page faults: cold segments assembled synchronously on the "
            "engine thread because prefetch had not staged them", ())
        self.kvpage_resident_bytes = r.gauge(
            "dyn_kvpage_resident_bytes",
            "Paged-lane working set in bytes by residency tier "
            "(device pages vs pinned host blocks)", ("tier", "worker"))
        self.kvpage_pagein_wait = r.histogram(
            "dyn_kvpage_pagein_wait_seconds",
            "Time the paged forward blocked waiting for a scheduled "
            "page-in to finish assembling (0 = fully overlapped)",
            (), buckets=LATENCY_BUCKETS_FAST)
        # scale plane (runtime/scale/): the hierarchical observer tree's
        # own health — region pre-merge cost per tick (the number the
        # hierarchy exists to keep flat as the fleet grows) — and the
        # sharded store client's per-shard degradation counter
        self.region_merge = r.histogram(
            "dyn_region_merge_seconds",
            "One regional aggregator tick: scrape the owned workers' "
            "stage dumps, pre-merge, publish the region record", (),
            buckets=LATENCY_BUCKETS_FAST + (2.5, 10.0))
        self.store_shard_errors = r.counter(
            "dyn_store_shard_errors_total",
            "Store calls that failed against one shard of a sharded "
            "store (that shard's families degraded; others unaffected)",
            ("shard",))
        # queue-until-boot (llm/http_service.py): scale-from-zero requests
        # parked at ingress until the planner boots a replica — parked is
        # also the planner's wake signal (counted into PoolSignals.unserved
        # alongside model-labelled 404s)
        self.queue_until_boot = r.counter(
            "dyn_queue_until_boot_total",
            "Scale-from-zero requests parked at HTTP ingress by outcome "
            "(parked|served|expired|overflow)", ("model", "outcome"))
        # flight-recorder plane (obs/): black-box ring health, watchdog
        # stall detections, and incident-bundle coordination — the
        # eviction counter is how a bundle consumer tells a quiet window
        # from a ring too small to cover it
        self.flightrec_evicted = r.counter(
            "dyn_flightrec_evicted_total",
            "Flight-recorder ring entries evicted before any incident "
            "captured them (spans|events|logtail)", ("ring",))
        self.watchdog_stalls = r.counter(
            "dyn_watchdog_stalls_total",
            "Hang-watchdog stall detections by kind (decode|transfer|"
            "drain|event_loop); each also emits a never-sampled "
            "stall:* span", ("kind",))
        self.incidents_captured = r.counter(
            "dyn_incidents_captured_total",
            "Incident capture beacons published, by trigger reason",
            ("reason",))
        self.incident_dumps = r.counter(
            "dyn_incident_dumps_total",
            "Flight-recorder ring dumps this process contributed to "
            "incident bundles", ())
        # byte-flow ledger (obs/flows.py): every byte-moving site —
        # disagg push/receive, cluster kv_fetch, paged page-in/out, h2d
        # prefetch, d2h write-through, weight prefetch, swap slabs —
        # accounts (src,dst,kind,bytes,seconds) through one chokepoint;
        # these series are its published face (dyntop links:, /v1/flows,
        # ctl flows all fold them back via flows_from_states)
        self.link_bytes = r.counter(
            "dyn_link_bytes_total",
            "Bytes moved per link and flow kind — network pairs are "
            "worker hex endpoints (src 'q' = anonymous prefill pool), "
            "host/device edges are host:<id> / dev:<id> / disk",
            ("src", "dst", "kind"))
        self.link_bw = r.gauge(
            "dyn_link_bw_bytes_per_s",
            "Windowed transfer rate per link: bytes recorded in the "
            "trailing DYN_LINK_WINDOW seconds over the window length",
            ("src", "dst"))
        self.link_saturation = r.gauge(
            "dyn_link_saturation",
            "Windowed link utilization vs calibrated capacity "
            "(DYN_LINK_CAPACITY_* override, else the link's measured "
            "peak rate), 0..1; link label is 'src>dst'", ("link",))
        self.link_congested = r.counter(
            "dyn_link_congested_total",
            "Rising-edge saturation crossings of DYN_LINK_SAT_THRESHOLD "
            "per link — each also emits a link.congested flight-recorder "
            "event and is incident-capture eligible", ("link",))

    def clear_worker(self, worker: str) -> None:
        """Drop every per-worker gauge series for ``worker`` (pid). Wired
        into engine shutdown/deregistration so a process that outlives its
        engine (shared-runtime tests, model remove/re-add) stops exporting
        ghost occupancy/MFU for an engine that no longer exists."""
        for g in (self.batch_occupancy, self.mfu, self.mbu, self.hbm_gbps):
            g.clear_label(0, worker)
        self.kv_tier_blocks.clear_label(1, worker)   # (tier, worker)
        self.kvpage_resident_bytes.clear_label(1, worker)


_stage: Optional[StageMetrics] = None
_stage_lock = threading.Lock()


def stage_metrics() -> StageMetrics:
    """Process-global :class:`StageMetrics` (lazily created)."""
    global _stage
    if _stage is None:
        with _stage_lock:
            if _stage is None:
                _stage = StageMetrics()
    return _stage
