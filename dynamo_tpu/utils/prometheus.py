"""Minimal Prometheus text-format metrics (no client library in the image).

Counters, gauges and histograms with labels, rendered in exposition format at
``/metrics``. Reference capability: lib/llm/src/http/service/metrics.rs and
components/metrics prometheus export.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0
)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help_: str, labels: Sequence[str]):
        self.name = name
        self.help = help_
        self.labels = tuple(labels)
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, labels=()):
        super().__init__(name, help_, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, *label_values: str) -> float:
        return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def clear_label(self, pos: int, value: str) -> None:
        """Drop every series whose label at ``pos`` equals ``value`` (e.g.
        re-exporting a component's worker set after a scrape: dead workers'
        series must vanish rather than freeze at their last value)."""
        v = str(value)
        with self._lock:
            for key in [k for k in self._values if k[pos] == v]:
                del self._values[key]

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(self.labels, key)} {v}")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, *label_values: str, value: float) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = value

    def dec(self, *label_values: str, amount: float = 1.0) -> None:
        self.inc(*label_values, amount=-amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, labels=(), buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, *label_values: str, value: float) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for key, counts in sorted(self._counts.items()):
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lbls = _fmt_labels(self.labels + ("le",), key + (repr(b).rstrip("0").rstrip("."),))
                out.append(f"{self.name}_bucket{lbls} {cum}")
            lbls_inf = _fmt_labels(self.labels + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lbls_inf} {self._totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.labels, key)} {self._sums[key]}")
            out.append(f"{self.name}_count{_fmt_labels(self.labels, key)} {self._totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []

    def counter(self, name, help_, labels=()) -> Counter:
        m = Counter(name, help_, labels)
        self._metrics.append(m)
        return m

    def gauge(self, name, help_, labels=()) -> Gauge:
        m = Gauge(name, help_, labels)
        self._metrics.append(m)
        return m

    def histogram(self, name, help_, labels=(), buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_, labels, buckets)
        self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"
