"""SLO burn-rate monitor: declarative objectives over the merged stage
histograms.

An objective is "fraction of good events >= objective" — e.g.
``DYN_SLO_TTFT_P90=0.5`` declares "90% of requests see TTFT <= 0.5s".
The monitor periodically snapshots the cumulative (total, bad) counts it
can derive from published metric state dumps (the same
``(component, state_dump)`` pairs ``render_states`` and the planner's
quantile estimator already consume), and computes **multi-window burn
rates**:

    burn(window) = bad_fraction(window) / error_budget
    error_budget = 1 - objective

burn == 1 means the error budget is being consumed exactly at the rate
that exhausts it over the SLO period; > 1 is over-budget (alert), >> 1 is
an incident. Multi-window (default 60s/5m/30m) is the standard SRE recipe:
the short window catches incidents fast, the long window stops flapping.

Exported as ``dyn_slo_burn_rate{slo,window}`` gauges on the process stage
registry (so whoever runs the monitor — planner, frontend, dyntop —
publishes it over the existing stage-metrics merge path) plus a bounded
**breach log** the planner's signal collector folds into
``PoolSignals.slo_burn`` as scale-up pressure.

Objectives (all optional; unset = not monitored):

- ``DYN_SLO_TTFT_P90``  — seconds; over ``llm_ttft_seconds``
- ``DYN_SLO_ITL_P90``   — seconds; over ``llm_inter_token_seconds``
- ``DYN_SLO_AVAILABILITY`` — good fraction (e.g. ``0.999``); bad events =
  5xx responses in ``dyn_http_requests_total`` (status label >= 500)
- ``DYN_SLO_WINDOWS``   — comma seconds, default ``60,300,1800``

Latency thresholds should sit on a histogram bucket edge (see
``LATENCY_BUCKETS_*`` in ``utils/prometheus.py``); an off-edge threshold
effectively rounds DOWN to the nearest lower edge — the whole bucket
containing it counts as bad. Over-counting bad events by at most one
bucket's width is the conservative direction: the monitor may over-alert
near the boundary, it never sleeps through a breach.
"""

from __future__ import annotations

import collections
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

DEFAULT_WINDOWS = (60.0, 300.0, 1800.0)


@dataclass(frozen=True)
class SloObjective:
    name: str                    # series label, e.g. "ttft_p90"
    objective: float             # target good fraction in (0, 1)
    metric: str                  # metric name in the state dumps
    threshold: Optional[float] = None   # latency bound (histogram SLOs)

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


def objectives_from_env(env: Optional[Dict[str, str]] = None
                        ) -> List[SloObjective]:
    e = os.environ if env is None else env
    out: List[SloObjective] = []

    def _f(key: str) -> Optional[float]:
        raw = e.get(key)
        if not raw:
            return None
        try:
            return float(raw)
        except ValueError:
            return None

    ttft = _f("DYN_SLO_TTFT_P90")
    if ttft is not None:
        out.append(SloObjective("ttft_p90", 0.90, "llm_ttft_seconds", ttft))
    itl = _f("DYN_SLO_ITL_P90")
    if itl is not None:
        out.append(SloObjective("itl_p90", 0.90,
                                "llm_inter_token_seconds", itl))
    avail = _f("DYN_SLO_AVAILABILITY")
    if avail is not None and 0.0 < avail < 1.0:
        out.append(SloObjective("availability", avail,
                                "dyn_http_requests_total"))
    return out


def windows_from_env(env: Optional[Dict[str, str]] = None
                     ) -> Tuple[float, ...]:
    raw = (os.environ if env is None else env).get("DYN_SLO_WINDOWS")
    if not raw:
        return DEFAULT_WINDOWS
    try:
        ws = tuple(sorted(float(x) for x in raw.split(",") if x.strip()))
        return ws or DEFAULT_WINDOWS
    except ValueError:
        return DEFAULT_WINDOWS


def _hist_totals(states: Iterable[Tuple[str, Dict]], metric: str,
                 threshold: float) -> Tuple[float, float]:
    """(total, bad) cumulative observation counts for a histogram metric
    across every dump/series: bad = observations above ``threshold``
    (counted from the per-bucket counts; the +Inf tail is total - sum)."""
    total = bad = 0.0
    for _component, dump in states:
        st = dump.get(metric)
        if not st or st.get("kind") != "histogram":
            continue
        buckets = list(st.get("buckets") or ())
        for series in st.get("series", {}).values():
            counts = series.get("counts") or []
            n = float(series.get("total", 0))
            total += n
            good = sum(c for b, c in zip(buckets, counts) if b <= threshold)
            bad += max(n - good, 0.0)
    return total, bad


def _availability_totals(states: Iterable[Tuple[str, Dict]], metric: str
                         ) -> Tuple[float, float]:
    """(total, bad) request counts from a status-labelled counter: bad =
    5xx. 4xx are the client's fault and don't burn the server's budget."""
    total = bad = 0.0
    for _component, dump in states:
        st = dump.get(metric)
        if not st or st.get("kind") != "counter":
            continue
        labels = list(st.get("labels") or ())
        try:
            pos = labels.index("status")
        except ValueError:
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if len(parts) <= pos:
                continue
            total += val
            try:
                if int(parts[pos]) >= 500:
                    bad += val
            except ValueError:
                pass
    return total, bad


@dataclass
class Breach:
    slo: str
    window: float
    burn: float
    at: float                     # wall-clock (time.time())

    def to_dict(self) -> Dict:
        return {"slo": self.slo, "window": self.window,
                "burn": round(self.burn, 3), "at": self.at}


_STAGE_GAUGE = object()   # default sentinel: export via stage_metrics()


class SloMonitor:
    """Feed :meth:`observe` one round of state dumps per tick; read burn
    rates from :meth:`burn_rates`, :attr:`breaches`, or the exported
    ``dyn_slo_burn_rate`` gauge. Pass ``registry_gauge=None`` to observe
    WITHOUT exporting (dyntop: a viewer must not write gauges a publishing
    process would then ship)."""

    def __init__(self, objectives: Optional[List[SloObjective]] = None,
                 windows: Optional[Tuple[float, ...]] = None,
                 registry_gauge=_STAGE_GAUGE, max_breaches: int = 256):
        self.objectives = (objectives_from_env() if objectives is None
                           else list(objectives))
        self.windows = tuple(windows or windows_from_env())
        if registry_gauge is _STAGE_GAUGE:
            from .prometheus import stage_metrics

            registry_gauge = stage_metrics().slo_burn
        self.gauge = registry_gauge
        # per-slo ring of (monotonic_ts, total, bad) snapshots, kept one
        # longest-window deep
        self._rings: Dict[str, collections.deque] = {
            o.name: collections.deque() for o in self.objectives}
        self.breaches: collections.deque = collections.deque(
            maxlen=max_breaches)
        self._last_burn: Dict[str, Dict[float, float]] = {}
        self._burning: Dict[Tuple[str, float], bool] = {}

    def observe(self, states: List[Tuple[str, Dict]],
                now: Optional[float] = None) -> Dict[str, Dict[float, float]]:
        """Snapshot cumulative counts from ``states`` and recompute burn
        rates for every (slo, window). Returns {slo: {window: burn}}."""
        now = time.monotonic() if now is None else now
        states = list(states)
        out: Dict[str, Dict[float, float]] = {}
        for o in self.objectives:
            if o.threshold is not None:
                total, bad = _hist_totals(states, o.metric, o.threshold)
            else:
                total, bad = _availability_totals(states, o.metric)
            ring = self._rings[o.name]
            ring.append((now, total, bad))
            horizon = now - max(self.windows) - 1.0
            while len(ring) > 2 and ring[1][0] < horizon:
                ring.popleft()
            out[o.name] = {}
            for w in self.windows:
                burn = self._burn(ring, now - w, total, bad, o)
                out[o.name][w] = burn
                if self.gauge is not None:
                    self.gauge.set(o.name, f"{int(w)}s", value=burn)
                if burn > 1.0:
                    self.breaches.append(
                        Breach(o.name, w, burn, time.time()))
                    # incident trigger on the breach EDGE only (sustained
                    # burn keeps appending breaches but must not re-open
                    # beacons every tick). Passive monitors (dyntop,
                    # gauge=None) observe without triggering.
                    if (self.gauge is not None
                            and not self._burning.get((o.name, w))):
                        from ..obs import incidents as _incidents

                        _incidents.trigger(
                            "slo_burn", slo=o.name, window=w,
                            burn=round(burn, 3))
                    self._burning[(o.name, w)] = True
                else:
                    self._burning[(o.name, w)] = False
        self._last_burn = out
        return out

    @staticmethod
    def _burn(ring, cutoff: float, total: float, bad: float,
              o: SloObjective) -> float:
        # baseline: the newest snapshot at or before the window start
        # (counts are cumulative, so deltas are exact regardless of how
        # often observe() ran). Before the window has history, the oldest
        # snapshot stands in — the burn is then over a shorter, honest span
        base_t, base_total, base_bad = ring[0]
        for ts, t_, b_ in ring:
            if ts <= cutoff:
                base_t, base_total, base_bad = ts, t_, b_
            else:
                break
        d_total = total - base_total
        d_bad = bad - base_bad
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / o.error_budget

    def burn_rates(self) -> Dict[str, Dict[float, float]]:
        """The most recent :meth:`observe` result."""
        return self._last_burn

    def max_burn(self) -> Dict[str, float]:
        """Per-slo worst burn across windows — the planner's scale-up
        pressure scalar."""
        return {slo: max(per_w.values()) if per_w else 0.0
                for slo, per_w in self._last_burn.items()}

    def recent_breaches(self, limit: int = 50) -> List[Dict]:
        return [b.to_dict() for b in list(self.breaches)[-limit:]]
