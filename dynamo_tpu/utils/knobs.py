"""Central registry of every ``DYN_*`` environment knob.

Every env var the system reads is declared here ONCE, with its type,
default, owning subsystem, and a one-line description. Two gates keep the
registry honest (rule ``knob-drift`` in ``dynamo_tpu/analysis``):

- every literal ``DYN_*`` name read anywhere under ``dynamo_tpu/`` +
  ``scripts/`` must have an entry here (an undeclared knob is an
  undocumented operational surface);
- every non-derived entry here must still be read somewhere (a stale
  entry is a knob operators set to no effect);
- ``docs/configuration.md`` is *generated* from this table
  (``python -m dynamo_tpu.utils.knobs --write``) and gated two-way
  against it, mirroring the metrics-catalog gate.

``derived=True`` marks knobs that never appear as literals in code: the
``utils/dynconfig.py`` layering materializes ``DYN_<PROG>_<FLAG>`` /
``DYN_<FLAG>`` names from CLI flags at argparse time (the planner's whole
``DYN_PLANNER_*`` surface works this way). They are registered so the doc
table is complete, and exempt from the must-be-read-literally check.

This module is stdlib-only and import-light on purpose — the lint
framework and tier-1 tests import it without touching jax or the runtime.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional

log = logging.getLogger("dynamo_tpu.knobs")


def env_float(name: str, default: float,
              env: Optional[Mapping[str, str]] = None,
              minimum: Optional[float] = None) -> float:
    """Parse a float knob, warning and falling back to ``default`` on a
    malformed (or, with ``minimum``, out-of-range) value — a bad env var
    must never crash a component at startup. This is the one shared copy
    of the parse policy, next to the registry the values are declared in.
    ``env`` overrides ``os.environ`` (tests pass a plain dict)."""
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return default
    try:
        val = float(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return default
    if minimum is not None and val < minimum:
        log.warning("ignoring out-of-range %s=%r (minimum %s)",
                    name, raw, minimum)
        return default
    return val

#: doc shorthand per subsystem (keeps the table rows terse)
_DOCS = {
    "runtime": "docs/robustness.md",
    "overload": "docs/robustness.md",
    "faults": "docs/robustness.md",
    "spec": "docs/speculative.md",
    "engine": "docs/observability.md",
    "tracing": "docs/observability.md",
    "metrics": "docs/observability.md",
    "store": "docs/observability.md",
    "fleet": "docs/observability.md",
    "logging": "docs/observability.md",
    "slo": "docs/observability.md",
    "roofline": "docs/observability.md",
    "multi_model": "docs/multi_model.md",
    "kvpage": "docs/long_context.md",
    "disagg": "docs/disagg_serving.md",
    "router": "docs/kv_cache_routing.md",
    "planner": "docs/planner.md",
    "sdk": "docs/architecture.md",
    "config": "docs/architecture.md",
    "llm": "docs/benchmarking.md",
}


@dataclass(frozen=True)
class Knob:
    name: str            # the full env var name, e.g. "DYN_LEASE_TTL"
    type: str            # str | int | float | bool | csv | json
    default: str         # human-readable default ("" = unset/off)
    subsystem: str       # key into _DOCS (owning plane)
    description: str     # one line, imperative, no trailing period
    derived: bool = False  # materialized by dynconfig flag layering

    @property
    def doc(self) -> str:
        return _DOCS[self.subsystem]


def _k(name: str, type: str, default: str, subsystem: str,
       description: str, derived: bool = False) -> Knob:
    return Knob(name, type, default, subsystem, description, derived)


_ALL: List[Knob] = [
    # ------------------------------------------------------------- runtime
    _k("DYN_STORE_RECONNECT", "bool", "1", "runtime",
       "store-client reconnect + session replay on connection loss"),
    _k("DYN_STORE_RECONNECT_ATTEMPTS", "int", "10", "runtime",
       "max reconnect attempts before the client reports closed"),
    _k("DYN_STORE_RECONNECT_BASE", "float", "0.05", "runtime",
       "reconnect backoff base delay, seconds (doubles per attempt)"),
    _k("DYN_STORE_RECONNECT_MAX", "float", "2.0", "runtime",
       "reconnect backoff ceiling, seconds"),
    _k("DYN_LEASE_TTL", "float", "10.0", "runtime",
       "store lease liveness TTL, seconds (keepalives fire every ttl/3)"),
    _k("DYN_DRAIN_TIMEOUT", "float", "10.0", "runtime",
       "graceful-drain grace on SIGTERM before cooperative stop, seconds"),
    _k("DYN_CB_THRESHOLD", "int", "3", "runtime",
       "consecutive failures that open an instance circuit breaker "
       "(0 disables)"),
    _k("DYN_CB_COOLDOWN", "float", "5.0", "runtime",
       "breaker OPEN hold before the half-open probe, seconds"),
    _k("DYN_RESUME_MAX", "int", "2", "runtime",
       "mid-stream failover budget: resume attempts per stream after a "
       "transport break or stall (0 disables resumable streams)"),
    _k("DYN_RESUME_STALL", "float", "30.0", "runtime",
       "inter-frame stall budget, seconds; a stream silent this long is "
       "treated as a break and resumed (0 disables stall detection)"),
    _k("DYN_REQUEST_TIMEOUT", "float", "", "runtime",
       "default end-to-end request deadline when the client sends none, "
       "seconds"),
    # ------------------------------------------------------------ overload
    _k("DYN_ADMIT_RPS", "float", "0", "overload",
       "token-bucket admission rate at HTTP ingress (0 = no rate cap)"),
    _k("DYN_ADMIT_BURST", "float", "2*rps", "overload",
       "token-bucket burst size"),
    _k("DYN_ADMIT_CONCURRENCY", "int", "0", "overload",
       "max in-flight requests admitted (0 = no concurrency cap)"),
    _k("DYN_ADMIT_QUEUE", "int", "-1", "overload",
       "admission wait-queue depth (-1 = unbounded, 0 = reject at cap)"),
    _k("DYN_ADMIT_BATCH_RESERVE", "float", "0.25", "overload",
       "fraction of admission capacity batch-priority traffic may use "
       "when interactive traffic is waiting"),
    _k("DYN_ADMIT_KV_BYTES", "float", "0", "overload",
       "in-flight KV byte budget at HTTP ingress: requests are priced "
       "at estimated tokens x DYN_ADMIT_KV_TOKEN_BYTES so one "
       "long-context request consumes its true share of the admission "
       "envelope (0 = dimension off)"),
    _k("DYN_ADMIT_KV_TOKEN_BYTES", "float", "0", "overload",
       "per-token KV price in bytes for the byte-honest admission "
       "dimension (2 * layers * kv_heads * head_dim * dtype_bytes of "
       "the served model; 0 = dimension off)"),
    _k("DYN_WORKER_SLOTS", "int", "0", "overload",
       "worker decode slot gate (0/unset = ungated)"),
    _k("DYN_WORKER_QUEUE_DEPTH", "int", "2*slots", "overload",
       "bounded wait queue behind the worker slot gate"),
    _k("DYN_WORKER_BATCH_QUEUE_DEPTH", "int", "-1", "overload",
       "batch-priority share of the worker wait queue (-1 = half)"),
    _k("DYN_BROWNOUT_MAX_TOKENS", "int", "256", "overload",
       "max_tokens ceiling applied at brownout level 2+"),
    _k("DYN_BROWNOUT_UP_BURN", "float", "2.0", "overload",
       "worst-SLO burn rate that steps the brownout ladder up"),
    _k("DYN_BROWNOUT_DOWN_BURN", "float", "0.75", "overload",
       "burn rate below which the ladder steps back down"),
    _k("DYN_BROWNOUT_DWELL_UP", "float", "5.0", "overload",
       "min seconds between upward brownout steps"),
    _k("DYN_BROWNOUT_DWELL_DOWN", "float", "30.0", "overload",
       "min seconds between downward brownout steps"),
    _k("DYN_BROWNOUT_MAX_LEVEL", "int", "3", "overload",
       "highest brownout level the controller may reach (ladder max 4)"),
    _k("DYN_TENANT_QUOTAS", "json", "", "overload",
       "static per-tenant admission quotas at HTTP ingress, e.g. "
       "'{\"acme\": {\"rps\": 5, \"burst\": 10, \"concurrency\": 8}}'; "
       "merged with (and overridden by) the fleet registry's per-model "
       "tenant tables"),
    _k("DYN_TENANT_AVAILABILITY", "float", "", "overload",
       "per-tenant good-request fraction objective (e.g. 0.99); when "
       "set, the worst tenant's burn also steps the brownout ladder"),
    _k("DYN_BOOT_WAIT", "float", "0", "multi_model",
       "queue-until-boot: max seconds a request for a fleet-registered "
       "scaled-to-zero model parks at HTTP ingress waiting for a "
       "replica to boot, bounded by the request deadline "
       "(0 = off, immediate 404 as before)"),
    _k("DYN_BOOT_WAIT_QUEUE", "int", "64", "multi_model",
       "max requests parked by queue-until-boot at once; beyond it "
       "requests get an immediate typed 503 (boot_queue_full)"),
    # --------------------------------------------------------- multi-model
    _k("DYN_FLEET_PREEMPT_MARGIN", "float", "0.5", "multi_model",
       "SLO-burn advantage a model needs before the chip arbiter "
       "preempts another model's live replicas (hysteresis against "
       "replica thrash; higher priority classes preempt regardless)"),
    _k("DYN_WEIGHT_CACHE_BYTES", "int", str(32 << 30), "multi_model",
       "per-worker pinned host-RAM weight cache budget (model "
       "mobility): sibling checkpoints prefetch here while the "
       "incumbent serves, so a hot-swap pays only the h2d stream"),
    _k("DYN_SWAP_GROUP_LAYERS", "int", "4", "multi_model",
       "layers per h2d group during a weight hot-swap (each group is "
       "one donated in-place slab scatter on the engine's existing "
       "device buffers)"),
    _k("DYN_SWAP_DRAIN_TIMEOUT", "float", "120", "multi_model",
       "seconds a swap command waits for in-flight streams to drain "
       "before falling back to a counted full reload (never a hang)"),
    # -------------------------------------------------------------- faults
    _k("DYN_FAULTS", "csv", "", "faults",
       "fault-injection table armed at process start, "
       "e.g. 'store.connect:refuse,kv.push.part:drop:0.5'"),
    # ---------------------------------------------------------------- spec
    _k("DYN_SPEC", "str", "", "spec",
       "speculative decoding mode: '' (off) | ngram | draft"),
    _k("DYN_SPEC_K", "int", "4", "spec",
       "max draft tokens per lane per dispatch"),
    _k("DYN_SPEC_K_MIN", "int", "1", "spec", "adaptive-k floor"),
    _k("DYN_SPEC_ADAPT", "bool", "1", "spec",
       "per-lane adaptive k on acceptance history"),
    _k("DYN_SPEC_NGRAM_MAX", "int", "3", "spec",
       "longest suffix n-gram the prompt-lookup proposer tries"),
    _k("DYN_SPEC_NGRAM_MIN", "int", "1", "spec",
       "shortest suffix n-gram fallback"),
    _k("DYN_SPEC_NGRAM_WINDOW", "int", "2048", "spec",
       "trailing-token window the n-gram proposer indexes"),
    _k("DYN_SPEC_DRAFT", "str", "", "spec",
       "draft model preset name or checkpoint dir (mode=draft)"),
    # -------------------------------------------------------- KV paging
    _k("DYN_KVPAGE_DEVICE_BUDGET", "int", "0", "kvpage",
       "device KV pages the paged long-context lane may hold resident "
       "(0 = KV paging off; engine-config kvpage_budget overrides)"),
    _k("DYN_KVPAGE_SEG_PAGES", "int", "8", "kvpage",
       "cold KV blocks per staged h2d upload segment"),
    _k("DYN_KVPAGE_PREFETCH", "int", "2", "kvpage",
       "segments the page-in thread assembles ahead of the attention "
       "pass (0 = synchronous page-ins, every one a counted fault)"),
    _k("DYN_KVPAGE_MAX_CONTEXT", "int", "131072", "kvpage",
       "context ceiling of the paged lane, tokens (the dense path's "
       "max_context still governs normal requests)"),
    _k("DYN_KVPAGE_DECODE_STEPS", "int", "4", "kvpage",
       "paged-lane decode tokens chained on-device per host fetch "
       "(sampled token feeds the next forward without a round-trip; "
       "1 = per-token synchronous as before)"),
    _k("DYN_KVPAGE_BATCH", "int", "1", "kvpage",
       "concurrent paged decode lanes sharing the device budget: each "
       "lane gets budget/batch pages and one batched dispatch serves a "
       "window step for every lane, with cold segments lane-stacked "
       "into shared staging slots (engine-config kvpage_batch "
       "overrides; 1 = the serial lane)"),
    # -------------------------------------------------------------- engine
    _k("DYN_PROFILE_DIR", "str", "", "engine",
       "capture an XLA profile of the first working iterations into "
       "this directory"),
    _k("DYN_PROFILE_STEPS", "int", "32", "engine",
       "engine iterations the DYN_PROFILE_DIR capture spans"),
    # ----------------------------------------------------- tracing/logging
    _k("DYN_TRACING", "bool", "1", "tracing",
       "request span tracing (0 disables recording entirely)"),
    _k("DYN_TRACE_BUFFER", "int", "4096", "tracing",
       "per-process span ring-buffer capacity"),
    _k("DYN_TRACE_SAMPLE", "float", "1.0", "tracing",
       "trace-id-consistent head-sampling fraction exported to the store "
       "span sink; error/deadline/breaker traces are always kept"),
    # --------------------------------------- flight recorder / watchdog
    _k("DYN_FLIGHTREC", "bool", "1", "tracing",
       "always-on flight recorder: per-process black-box rings dumped "
       "into incident bundles (0 = record nothing)"),
    _k("DYN_FLIGHTREC_SPANS", "int", "2048", "tracing",
       "flight-recorder span ring capacity (every finished span, "
       "including head-sampled-out ones)"),
    _k("DYN_FLIGHTREC_EVENTS", "int", "4096", "tracing",
       "flight-recorder event ring capacity (engine step timings, gate "
       "waits, transfer EWMA snapshots, store health transitions)"),
    _k("DYN_FLIGHTREC_LOGTAIL", "int", "256", "tracing",
       "flight-recorder structured-log tail capacity"),
    _k("DYN_WATCHDOG", "bool", "1", "tracing",
       "hang watchdog: stall:* span emission + incident triggers "
       "(0 = heartbeats are recorded but never judged)"),
    _k("DYN_WATCHDOG_INTERVAL", "float", "0.25", "tracing",
       "watchdog poll period, seconds (its own tick lag is the "
       "event-loop-stall probe)"),
    _k("DYN_WATCHDOG_MULT", "float", "8.0", "tracing",
       "stall threshold as a multiple of an activity's EWMA unit time "
       "(a decode dispatch exceeding mult x EWMA step time is wedged)"),
    _k("DYN_WATCHDOG_FLOOR", "float", "1.0", "tracing",
       "absolute floor, seconds, under the EWMA-multiple threshold — a "
       "noisy sub-millisecond EWMA must not yield false stalls"),
    _k("DYN_WATCHDOG_TRANSFER", "float", "5.0", "tracing",
       "no-layer-progress budget for an in-flight disagg KV stream, "
       "seconds, before stall:transfer fires"),
    _k("DYN_WATCHDOG_LOOP_STALL", "float", "1.0", "tracing",
       "event-loop stall threshold: watchdog tick lateness, seconds"),
    _k("DYN_INCIDENT_TTL", "float", "3600", "tracing",
       "incident beacon + bundle lease TTL, seconds"),
    _k("DYN_INCIDENT_COOLDOWN", "float", "30", "tracing",
       "triggers raised within this many seconds of a live beacon "
       "attach to that incident instead of opening a new one"),
    _k("DYN_INCIDENT_WINDOW", "float", "30", "tracing",
       "ring-slice window dumped into a bundle, seconds before the "
       "trigger"),
    # ------------------------------------------------------------- metrics
    _k("DYN_METRICS_PUSH_INTERVAL", "float", "0", "metrics",
       "min seconds between a worker's stage-metrics store writes "
       "(0 = every metrics-loop beat); writes are delta-coalesced either "
       "way"),
    _k("DYN_METRICS_FULL_EVERY", "int", "10", "metrics",
       "stage-metrics pushes per full snapshot (the rest ship only "
       "changed metrics)"),
    _k("DYN_STAGE_SLICES", "int", "16", "metrics",
       "worker-stable sub-prefix slices of the metrics_stage/ keyspace "
       "(worker_id mod slices); regional aggregators rendezvous-own "
       "slices and read only theirs per tick instead of scanning the "
       "full prefix (must agree fleet-wide)"),
    # byte-flow ledger (obs/flows.py): the per-process accounting
    # chokepoint every byte-moving site records through
    _k("DYN_FLOWS", "bool", "1", "metrics",
       "byte-flow ledger master switch; 0 disables all link accounting "
       "(the flows_overhead A/B arm)"),
    _k("DYN_LINK_WINDOW", "float", "10.0", "metrics",
       "trailing window for per-link rate/saturation, seconds"),
    _k("DYN_LINK_SAT_THRESHOLD", "float", "0.9", "metrics",
       "saturation level whose rising edge emits a link.congested "
       "flight-recorder event and bumps dyn_link_congested_total"),
    _k("DYN_LINK_CAPACITY_NET", "float", "0", "metrics",
       "calibrated capacity for network (worker-pair) links, bytes/s "
       "(0 = use each link's measured peak rate)"),
    _k("DYN_LINK_CAPACITY_H2D", "float", "0", "metrics",
       "calibrated capacity for host-to-device links, bytes/s "
       "(0 = measured peak)"),
    _k("DYN_LINK_CAPACITY_D2H", "float", "0", "metrics",
       "calibrated capacity for device-to-host links, bytes/s "
       "(0 = measured peak)"),
    _k("DYN_LINK_CAPACITY_DISK", "float", "0", "metrics",
       "calibrated capacity for disk/checkpoint-read links, bytes/s "
       "(0 = measured peak)"),
    # --------------------------------------------------------------- store
    _k("DYN_STORE_METRICS_INTERVAL", "float", "2.0", "store",
       "seconds between the store server's self-telemetry dumps into its "
       "own KV (0 = record but never publish)"),
    _k("DYN_STORE_SHARDS", "str", "", "store",
       "static store shard map routing keyspace families/groups to "
       "extra dynstore processes, e.g. "
       "'telemetry=10.0.0.2:4222;traces=10.0.0.3:4222' (unset = the "
       "single default store; unrouted families stay on it)"),
    # --------------------------------------------------------------- scale
    _k("DYN_REGION_INTERVAL", "float", "2.0", "store",
       "seconds between a regional aggregator's pre-merge ticks (one "
       "region record published per tick)"),
    _k("DYN_REGION_STALE", "float", "3*interval", "store",
       "age in seconds beyond which observers treat a region record as "
       "dead and fall back to the flat per-worker scrape"),
    _k("DYN_LOG", "str", "info", "logging",
       "root log level, with per-target overrides "
       "('info,dynamo_tpu.runtime=debug')"),
    _k("DYN_LOGGING_JSONL", "str", "", "logging",
       "JSONL log output: '1'/'stderr' = JSON lines on stderr, "
       "other values = file path"),
    # ----------------------------------------------------------------- slo
    _k("DYN_SLO_TTFT_P90", "float", "", "slo",
       "TTFT p90 objective, seconds (unset = SLO not monitored)"),
    _k("DYN_SLO_ITL_P90", "float", "", "slo",
       "inter-token latency p90 objective, seconds"),
    _k("DYN_SLO_AVAILABILITY", "float", "", "slo",
       "good-request fraction objective, e.g. 0.999"),
    _k("DYN_SLO_WINDOWS", "csv", "60,300,1800", "slo",
       "burn-rate windows, seconds"),
    # ------------------------------------------------------------ roofline
    _k("DYN_PEAK_FLOPS", "float", "", "roofline",
       "override peak accelerator FLOP/s for MFU accounting"),
    _k("DYN_PEAK_GBPS", "float", "", "roofline",
       "override peak HBM GB/s for MBU accounting"),
    # -------------------------------------------------------------- disagg
    _k("DYN_PREFILL_QUEUE_MAX", "int", "0", "disagg",
       "bounded shared prefill queue depth (0 = unbounded)"),
    _k("DYN_PREFILL_QUEUE_MAX_BATCH", "int", "max/2", "disagg",
       "batch-priority share of the prefill queue"),
    _k("DYN_KV_STREAM", "bool", "1", "disagg",
       "layer-streamed disagg KV ingestion: each arriving layer's device "
       "scatter is enqueued while later layers are in flight (0 = legacy "
       "full-arrival import; the bench A/B switch)"),
    _k("DYN_KV_BW_ALPHA", "float", "0.3", "disagg",
       "EWMA weight of a new per-pair KV-transfer bandwidth observation "
       "(llm_kv_pair_bw_bytes_per_s)"),
    # -------------------------------------------------------------- router
    _k("DYN_ROUTER_FAST_FAIL", "bool", "0", "router",
       "fail saturated scheduling with a typed 503 instead of "
       "capacity-waiting"),
    _k("DYN_ROUTER_AUDIT", "int", "512", "router",
       "router decision audit ring capacity"),
    _k("DYN_KV_CLUSTER", "bool", "0", "router",
       "cluster-wide KV sharing: workers publish sealed-block registry "
       "records + serve/consume kv_fetch, routers stamp donors"),
    _k("DYN_KV_CLUSTER_PUBLISH_INTERVAL", "float", "1.0", "router",
       "min seconds between a worker's registry record writes "
       "(seal/evict-driven, write-coalesced)"),
    _k("DYN_KV_CLUSTER_FETCH_TIMEOUT", "float", "5.0", "router",
       "peer prefix fetch budget, seconds; expiry falls back to local "
       "prefill recompute"),
    _k("DYN_KV_CLUSTER_MAX_BLOCKS", "int", "0", "router",
       "cap on KV blocks per peer fetch, donor and receiver side "
       "(0 = unlimited)"),
    _k("DYN_KV_CLUSTER_PEER_WEIGHT", "float", "0.5", "router",
       "score value of a free peer-held block relative to a local block "
       "(discounted further by estimated transfer time)"),
    _k("DYN_ROUTER_TRANSFER_WEIGHT", "float", "1.0", "router",
       "logit penalty per expected KV-transfer second of a placement "
       "(bytes-to-move x measured per-pair bandwidth; 0 = transfer-cost "
       "term off)"),
    _k("DYN_H2D_PREFETCH_BLOCKS", "int", "32", "router",
       "device staging blocks for placement-driven h2d prefetch of "
       "matched tier prefixes while a request queues at the slot gate "
       "(0 = prefetch off, admission uploads synchronously as before)"),
    # ----------------------------------------------------------------- llm
    _k("DYN_TOKEN_ECHO_DELAY_MS", "float", "10", "llm",
       "echo-engine per-token pacing, milliseconds (0 = as fast as "
       "possible; test/bench fixture)"),
    # ------------------------------------------------------------- sdk
    _k("DYN_SERVICE_CONFIG", "json", "", "sdk",
       "service-graph config JSON injected into sdk.serve children"),
    _k("DYN_SERVICE_CONFIG_FILE", "str", "", "sdk",
       "path to the service config JSON (set by deploy manifests)"),
    # ------------------------------------------------- dynconfig (derived)
    _k("DYN_PORT", "int", "per-flag", "config",
       "global flag override: DYN_<FLAG> applies to every binary's "
       "matching --flag", derived=True),
    _k("DYN_HTTP_PORT", "int", "per-flag", "config",
       "binary-scoped flag override (DYN_<PROG>_<FLAG>); set by deploy "
       "manifests for the frontend port", derived=True),
]

# The planner daemon's whole flag surface is env-drivable as
# DYN_PLANNER_<FLAG> through the dynconfig layering — registered here so
# docs/configuration.md lists every operator-facing knob.
_PLANNER = [
    ("STORE", "str", "127.0.0.1:4222", "store host:port"),
    ("NAMESPACE", "str", "dynamo", "runtime namespace"),
    ("DECODE_COMPONENT", "str", "backend", "decode pool component"),
    ("PREFILL_COMPONENT", "str", "", "prefill pool component "
                                     "('' = decode only)"),
    ("POLICY", "str", "load", "scaling policy: load | sla"),
    ("CONNECTOR", "str", "none", "actuator: local | kube | none"),
    ("INTERVAL", "float", "2.0", "control-loop period, seconds"),
    ("MIN_REPLICAS", "int", "1", "per-pool replica floor"),
    ("MAX_REPLICAS", "int", "8", "per-pool replica ceiling"),
    ("COOLDOWN_UP", "float", "30.0", "min seconds between scale-ups"),
    ("COOLDOWN_DOWN", "float", "120.0", "min seconds between scale-downs"),
    ("DOWN_CONSENSUS", "int", "3", "consecutive down-votes before a "
                                   "scale-down actuates"),
    ("DRY_RUN", "bool", "0", "publish decisions but never actuate"),
    ("FLEET", "bool", "0", "reconcile the multi-model fleet registry "
                           "(pool set follows ctl fleet add/remove, "
                           "targets pass the chip arbiter)"),
    ("BROWNOUT", "bool", "0", "run the SLO-burn brownout controller on "
                              "the planner loop"),
    ("QUEUE_HIGH", "float", "1.0", "load policy: queue-depth-per-replica "
                                   "scale-up threshold"),
    ("OCCUPANCY_HIGH", "float", "0.85", "load policy: slot occupancy "
                                        "scale-up threshold"),
    ("OCCUPANCY_LOW", "float", "0.3", "load policy: slot occupancy "
                                      "scale-down threshold"),
    ("KV_HIGH", "float", "0.9", "load policy: KV occupancy scale-up "
                                "threshold"),
    ("KV_LOW", "float", "0.5", "load policy: KV occupancy scale-down "
                               "threshold"),
    ("PROFILE", "str", "", "SLA policy: profile table path "
                           "(planner.profile output)"),
    ("TTFT_TARGET", "float", "2.0", "SLA policy: TTFT target, seconds"),
    ("ITL_TARGET", "float", "0.05", "SLA policy: inter-token target, "
                                    "seconds"),
    ("WORKER_ENGINE", "str", "jax", "local connector: engine for spawned "
                                    "workers"),
    ("WORKER_CHIPS", "int", "0", "local connector: chips per decode "
                                 "worker (0 = auto)"),
    ("PREFILL_WORKER_CHIPS", "int", "0", "local connector: chips per "
                                         "prefill worker"),
    ("TOTAL_CHIPS", "int", "4", "local connector: chip budget for the "
                                "sdk allocator"),
    ("PLATFORM", "str", "cpu", "local connector: cpu | tpu"),
    ("WORKER_ARGS", "str", "", "local connector: extra args appended to "
                               "spawned worker command lines"),
    ("KUBE_URL", "str", "", "kube connector: API server URL"),
    ("KUBE_TOKEN", "str", "", "kube connector: bearer token"),
    ("KUBE_INSECURE", "bool", "0", "kube connector: skip TLS verify"),
    ("KUBE_NAMESPACE", "str", "default", "kube connector: namespace"),
    ("KUBE_DEPLOYMENT", "str", "", "kube connector: DynamoDeployment / "
                                   "Deployment name"),
    ("KUBE_MODE", "str", "crd", "kube connector: crd | deployment"),
]
_ALL.extend(
    _k(f"DYN_PLANNER_{flag}", typ, default, "planner", desc, derived=True)
    for flag, typ, default, desc in _PLANNER)

# The regional aggregator daemon (cli/aggregator.py) resolves its flags
# through the dynconfig layering as DYN_AGGREGATOR_<FLAG>.
_AGGREGATOR = [
    ("STORE", "str", "127.0.0.1:4222", "store host:port"),
    ("NAMESPACE", "str", "dynamo", "namespace whose workers this "
                                   "aggregator's region tree covers"),
    ("INTERVAL", "float", "DYN_REGION_INTERVAL", "seconds between "
                                                 "region merges"),
]
_ALL.extend(
    _k(f"DYN_AGGREGATOR_{flag}", typ, default, "store", desc,
       derived=True)
    for flag, typ, default, desc in _AGGREGATOR)

# The fleet-soak rig (scripts/fleet_soak.py) resolves its flags through
# the same dynconfig layering as DYN_FLEET_SOAK_<FLAG>.
_FLEET_SOAK = [
    ("WORKERS", "int", "600", "final synthetic-worker count of the ramp"),
    ("STEPS", "int", "4", "ramp steps (worker counts spaced evenly up to "
                          "--workers)"),
    ("STEP_DURATION", "float", "8.0", "measured seconds per ramp step"),
    ("BEAT_INTERVAL", "float", "2.0", "synthetic worker metrics/span "
                                      "beat period"),
    ("BEACON_INTERVAL", "float", "0.5", "seconds between fan-out beacon "
                                        "puts"),
    ("SPANS_PER_BEAT", "int", "4", "spans each synthetic worker emits "
                                   "per beat"),
    ("TRACE_SAMPLE", "float", "0.01", "DYN_TRACE_SAMPLE armed fleet-wide "
                                      "for the soak"),
    ("TRAFFIC_RPS", "float", "4.0", "real replayed-traffic rate through "
                                    "router+frontend (0 = store-only "
                                    "soak, no serving procs)"),
    ("REAL_WORKERS", "int", "2", "echo workers actually serving the "
                                 "replayed traffic"),
    ("KNEE_MULT", "float", "4.0", "saturation-knee threshold: first step "
                                  "whose store op p99 exceeds this "
                                  "multiple of the first step's"),
    ("OUT", "str", "bench_points/fleet_soak.json", "artifact path "
                                                   "(hier mode defaults "
                                                   "to fleet_soak_hier"
                                                   ".json)"),
    ("MODE", "str", "flat", "observer path under test: flat (per-worker "
                            "scrape) or hier (regional aggregators + "
                            "region records)"),
    ("AGGREGATORS", "int", "4", "regional aggregator daemons spawned in "
                                "hier mode"),
    ("SHARDS", "int", "1", "dynstore processes: 2 adds a telemetry "
                           "shard, 3 adds a traces shard too "
                           "(DYN_STORE_SHARDS armed fleet-wide)"),
]
_ALL.extend(
    _k(f"DYN_FLEET_SOAK_{flag}", typ, default, "fleet", desc, derived=True)
    for flag, typ, default, desc in _FLEET_SOAK)

KNOBS: Dict[str, Knob] = {k.name: k for k in _ALL}
if len(KNOBS) != len(_ALL):
    raise RuntimeError("duplicate knob registration")


def render_markdown() -> str:
    """The generated body of docs/configuration.md."""
    out = [
        "# Configuration — the `DYN_*` environment knob surface",
        "",
        "<!-- GENERATED FILE — do not edit by hand. "
        "Regenerate: python -m dynamo_tpu.utils.knobs --write -->",
        "",
        "Every environment variable the system reads, generated from the",
        "central registry in `dynamo_tpu/utils/knobs.py` and gated two-way",
        "against it by the `knob-drift` rule (`python scripts/dynalint.py`;",
        "see [static analysis](static_analysis.md)). Add a knob by",
        "registering it there, then regenerate this file.",
        "",
        "Knobs marked *derived* are materialized from CLI flags by the",
        "`utils/dynconfig.py` layering (`DYN_<PROG>_<FLAG>` beats",
        "`DYN_<FLAG>` beats the built-in default); the rest are read",
        "directly by the owning subsystem at the moment listed in its doc.",
        "",
    ]
    by_sub: Dict[str, List[Knob]] = {}
    for k in KNOBS.values():
        by_sub.setdefault(k.subsystem, []).append(k)
    for sub in sorted(by_sub):
        knobs = sorted(by_sub[sub], key=lambda k: k.name)
        doc = _DOCS[sub]
        out.append(f"## {sub} ([{doc.split('/')[-1]}]"
                   f"({doc.split('/')[-1]}))")
        out.append("")
        out.append("| knob | type | default | description |")
        out.append("|---|---|---|---|")
        for k in knobs:
            d = k.default if k.default != "" else "*(unset)*"
            desc = k.description + (" *(derived)*" if k.derived else "")
            out.append(f"| `{k.name}` | {k.type} | `{d}` | {desc} |")
        out.append("")
    out.append(f"{len(KNOBS)} knobs registered.")
    out.append("")
    return "\n".join(out)


def _main(argv: List[str]) -> int:
    import os
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    target = os.path.join(repo, "docs", "configuration.md")
    if "--write" in argv:
        with open(target, "w", encoding="utf-8") as f:
            f.write(render_markdown())
        print(f"wrote {target} ({len(KNOBS)} knobs)")
    else:
        print(render_markdown())
    return 0


if __name__ == "__main__":          # pragma: no cover - trivial shell
    import sys
    sys.exit(_main(sys.argv[1:]))
