"""Forcing the host-CPU platform with N virtual devices.

Single home for the axon-plugin workaround used by tests/conftest.py,
__graft_entry__.py and bench.py: the axon TPU PJRT plugin overrides the
``JAX_PLATFORMS`` env var at import time (the ``jax_platforms`` config flag
wins over it), and its backend init can hang or fail when the TPU tunnel is
down — so anything that wants the CPU platform must force it *before* any
backend touch and never let the plugin initialize.

XLA parses ``--xla_force_host_platform_device_count`` once per process, at
first backend creation: growing the device count after a backend exists is
impossible in-process (``jax_num_cpu_devices`` likewise refuses post-init).
:func:`force_cpu` therefore reports whether the live process satisfies the
request so callers can re-exec in a fresh interpreter when it does not.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def set_host_device_count_env(n: int) -> None:
    """Ensure ``XLA_FLAGS`` requests >= n virtual host devices. Env-only —
    safe to call before jax is imported (e.g. from conftest)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m:
        if int(m.group(1)) < n:
            flags = re.sub(rf"--{_FLAG}=\d+", f"--{_FLAG}={n}", flags)
    else:
        flags = (flags + f" --{_FLAG}={n}").strip()
    os.environ["XLA_FLAGS"] = flags


def honor_jax_platforms_env() -> None:
    """Some PJRT plugins (axon) override the JAX_PLATFORMS env var at
    import; re-assert the operator's choice via the config flag, which
    wins. Call before any backend init in every CLI entry point."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and plat != "axon":
        import jax

        jax.config.update("jax_platforms", plat)


def force_cpu(n_devices: int = 1) -> bool:
    """Force the cpu platform with >= n_devices virtual devices.

    Returns True when this process now sees enough CPU devices; False when a
    backend was already initialized with fewer devices (the flag is parsed
    once per process — the caller must re-exec in a fresh interpreter).
    """
    import jax

    try:
        from jax._src import xla_bridge

        live = bool(xla_bridge._backends)  # noqa: SLF001 — no public probe
    # dynalint: ok(swallowed-exception) probe of a jax-internal attr:
    # "can't tell" and "no backend" get the same safe answer (live=False)
    except Exception:
        live = False
    if live:
        # A backend is already initialized: the flag was parsed, the count
        # cannot change, and force-switching platforms would break the
        # caller's live arrays. Mutate nothing — report whether the current
        # state already satisfies the request (caller re-execs otherwise).
        return (jax.default_backend() == "cpu"
                and jax.device_count() >= n_devices)

    os.environ["JAX_PLATFORMS"] = "cpu"
    set_host_device_count_env(n_devices)
    jax.config.update("jax_platforms", "cpu")
    return len(jax.devices()) >= n_devices
