"""Structured logging: DYN_LOG level filter, JSONL output, request-id spans.

- :func:`init_logging` configures the root logger from ``DYN_LOG``
  (level, e.g. ``debug`` or ``dynamo_tpu.engine=debug,info``) and
  ``DYN_LOGGING_JSONL`` ("1"/"stderr" => JSON lines on stderr, any other
  value => append to that file path).
- :data:`request_id_var` is a contextvar carried across the async call
  chain; the data plane sets it server-side from the wire ``context_id`` and
  the HTTP frontend sets it at ingress, so one request's log lines share an
  id across frontend -> router -> worker processes.

Reference capability: lib/runtime/src/logging.rs:94-138 (DYN_LOG env filter +
JSONL event formatter) and the request_id span fields the preprocessor
attaches (lib/llm/src/preprocessor.rs:198-233).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import sys
import time
from typing import Optional

request_id_var: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("dynamo_request_id", default=None)


class RequestIdFilter(logging.Filter):
    """Attaches the current request id to every record (as ``request_id``)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id_var.get()
        return True


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        rid = getattr(record, "request_id", None)
        if rid:
            out["request_id"] = rid
        if record.exc_info and record.exc_info[0] is not None:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def _parse_dyn_log(spec: str):
    """``info`` or ``some.module=debug,warning`` -> (root level, overrides)."""
    root = None
    overrides = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            mod, lvl = part.split("=", 1)
            overrides[mod.strip()] = lvl.strip().upper()
        else:
            root = part.upper()
    return root or "INFO", overrides


def init_logging(stream=None) -> None:
    """Configure logging from DYN_LOG / DYN_LOGGING_JSONL. Idempotent."""
    root_level, overrides = _parse_dyn_log(os.environ.get("DYN_LOG", "info"))
    jsonl = os.environ.get("DYN_LOGGING_JSONL", "")

    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_dynamo_tpu", False):
            root.removeHandler(h)

    if jsonl and jsonl not in ("0", "false"):
        if jsonl in ("1", "true", "stderr"):
            handler = logging.StreamHandler(stream or sys.stderr)
        else:
            handler = logging.FileHandler(jsonl)
        handler.setFormatter(JsonlFormatter())
    else:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s%(request_tag)s %(message)s"))

        class _TagFilter(logging.Filter):
            def filter(self, record):
                # read the contextvar directly: filters run in insertion
                # order, so relying on RequestIdFilter having run would
                # silently drop the id in plain-text mode
                rid = request_id_var.get()
                record.request_tag = f" [{rid}]" if rid else ""
                return True

        handler.addFilter(_TagFilter())
    handler.addFilter(RequestIdFilter())
    handler._dynamo_tpu = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.setLevel(root_level)
    for mod, lvl in overrides.items():
        logging.getLogger(mod).setLevel(lvl)
