"""Fault injection for chaos testing the serving plane.

Named fault *points* are compiled into the runtime's network paths (store
connect, store calls, data-plane connect, KV push parts, prefill compute).
Each point is a no-op until armed, so production cost is one dict lookup.

Arming, two ways:

- **Environment** — ``DYN_FAULTS`` at process start, comma-separated:

      DYN_FAULTS="store.connect:refuse,kv.push.part:drop:0.5"

  Entry grammar: ``point:action[:num[:rate]]``. Actions:

  - ``refuse``       raise ``ConnectionRefusedError`` (num = rate)
  - ``drop``         raise ``ConnectionResetError``   (num = rate)
  - ``error``        raise ``RuntimeError``           (num = rate)
  - ``delay``        sleep ``num`` seconds (default 1.0), then proceed
                     (4th field = rate)
  - ``stall``        sleep ``num`` seconds (default 3600) — an effective
                     hang, for exercising deadline enforcement

  ``rate`` in [0,1] fires the fault probabilistically (default 1 = always).

- **Store** — :func:`watch_store_faults` watches the ``faults/`` prefix;
  key ``faults/<point>`` holds the ``action[:num[:rate]]`` tail. Put/delete
  arms/disarms live across the whole cluster — the chaos harness's lever.

Every firing emits a ``fault:<point>`` span (visible in ``/v1/traces``) and
counts ``dyn_faults_injected_total{point,action}``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Dict, Optional

log = logging.getLogger("dynamo_tpu.faults")

FAULTS_PREFIX = "faults/"

_ACTIONS = ("refuse", "drop", "error", "delay", "stall")


@dataclass
class Fault:
    action: str
    num: float          # seconds for delay/stall; unused otherwise
    rate: float = 1.0


# process-global armed table: point -> Fault
_active: Dict[str, Fault] = {}
_env_loaded = False


def _parse_tail(point: str, tail: str) -> Optional[Fault]:
    """``action[:num[:rate]]`` -> Fault (None + log on malformed input)."""
    parts = tail.split(":")
    action = parts[0].strip()
    if action not in _ACTIONS:
        log.warning("ignoring fault %s: unknown action %r", point, action)
        return None
    default_num = 1.0 if action == "delay" else 3600.0
    try:
        if action in ("delay", "stall"):
            num = float(parts[1]) if len(parts) > 1 and parts[1] else \
                default_num
            rate = float(parts[2]) if len(parts) > 2 else 1.0
        else:
            num = 0.0
            rate = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
    except ValueError:
        log.warning("ignoring fault %s: malformed spec %r", point, tail)
        return None
    return Fault(action, num, min(max(rate, 0.0), 1.0))


def configure(spec: Optional[str] = None) -> Dict[str, Fault]:
    """Parse a ``DYN_FAULTS``-style spec, REPLACING the whole active table
    (``configure("")`` disarms everything, including store-driven entries).
    Called lazily with the env spec on first :func:`fire`."""
    global _env_loaded
    _env_loaded = True
    if spec is None:
        spec = os.environ.get("DYN_FAULTS", "")
    _active.clear()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        point, _, tail = entry.partition(":")
        f = _parse_tail(point, tail)
        if f is not None:
            _active[point] = f
            log.warning("fault armed: %s -> %s", point, f)
    return _active


def _ensure_loaded() -> None:
    # the env spec loads lazily; it must load BEFORE any programmatic
    # arm/watch so the replace-semantics of configure() can't wipe them
    if not _env_loaded:
        configure()


def arm(point: str, action: str, num: float = 0.0, rate: float = 1.0) -> None:
    _ensure_loaded()
    _active[point] = Fault(action, num, rate)


def disarm(point: Optional[str] = None) -> None:
    if point is None:
        _active.clear()
    else:
        _active.pop(point, None)


def is_active(point: str) -> Optional[Fault]:
    _ensure_loaded()
    return _active.get(point)


async def fire(point: str) -> None:
    """Execute the armed fault at ``point`` (no-op when unarmed). Raises the
    configured connection error, or sleeps for delay/stall."""
    f = is_active(point)
    if f is None:
        return
    if f.rate < 1.0 and random.random() >= f.rate:
        return
    from .prometheus import stage_metrics
    from .tracing import get_tracer

    stage_metrics().faults_injected.inc(point, f.action)
    t0 = time.time()
    log.warning("fault fired: %s -> %s", point, f)
    if f.action in ("delay", "stall"):
        await asyncio.sleep(f.num)
        get_tracer().record(f"fault:{point}", start=t0, end=time.time(),
                            action=f.action, seconds=f.num)
        return
    get_tracer().record(f"fault:{point}", start=t0, end=time.time(),
                        action=f.action)
    if f.action == "refuse":
        raise ConnectionRefusedError(f"fault injection: {point}")
    if f.action == "drop":
        raise ConnectionResetError(f"fault injection: {point}")
    raise RuntimeError(f"fault injection: {point}")


async def watch_store_faults(store) -> None:
    """Arm/disarm faults live from the store's ``faults/`` prefix (value =
    ``action[:num[:rate]]``). The cluster-wide chaos lever: every process
    that calls this follows the same table."""
    _ensure_loaded()

    async def on_change(key: str, value: Optional[bytes], deleted: bool):
        point = key[len(FAULTS_PREFIX):]
        if deleted:
            disarm(point)
            log.warning("fault disarmed (store): %s", point)
            return
        f = _parse_tail(point, value.decode())
        if f is not None:
            _active[point] = f
            log.warning("fault armed (store): %s -> %s", point, f)

    snapshot = await store.watch_prefix(FAULTS_PREFIX, on_change)
    for key, value in snapshot:
        await on_change(key, value, False)
