"""Retained background tasks: the sanctioned fire-and-forget pattern.

``asyncio.create_task`` as a bare statement drops the only reference to
the task: its exception is swallowed until GC (then surfaces as an
unactionable "Task exception was never retrieved"), and since the loop
holds tasks only weakly, the work itself can be collected mid-flight.
The dynalint ``fire-and-forget`` rule bans the bare form; this module is
what you call instead when a task really is launch-and-move-on:

    from ..utils.aiotasks import spawn
    spawn(self._publish(ev), name="kv-hit-rate")

:func:`spawn` keeps a strong reference in a registry until the task
settles, and logs any exception (cancellation excluded) so failures leave
a trace. Pass ``store=`` to use an owner-scoped registry you can drain on
shutdown (:func:`cancel_all`).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine, Optional, Set

log = logging.getLogger("dynamo_tpu.aiotasks")

#: default registry: strong refs for tasks with no owning object
_BACKGROUND: Set["asyncio.Task"] = set()


def spawn(coro: Coroutine[Any, Any, Any], *, name: Optional[str] = None,
          store: Optional[Set["asyncio.Task"]] = None) -> "asyncio.Task":
    """create_task + retention + exception logging, in one call."""
    registry = _BACKGROUND if store is None else store
    task = asyncio.ensure_future(coro)
    if name and hasattr(task, "set_name"):
        task.set_name(name)
    registry.add(task)

    def _done(t: "asyncio.Task") -> None:
        registry.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("background task %s died: %r",
                      name or getattr(t, "get_name", lambda: "?")(), exc)

    task.add_done_callback(_done)
    return task


async def cancel_all(store: Set["asyncio.Task"]) -> None:
    """Cancel and await every task in an owner-scoped registry (shutdown
    path: nothing may outlive its owner and log into a torn-down world)."""
    tasks = [t for t in store if not t.done()]
    for t in tasks:
        t.cancel()
    for t in tasks:
        try:
            await t
        # dynalint: ok(swallowed-exception) the done-callback already
        # logged any non-cancel exception; this await only reaps
        except (asyncio.CancelledError, Exception):
            pass


def spawn_blocking(fn, *args, name: Optional[str] = None):
    """Run a blocking callable on the default executor as a RETAINED
    future — concurrent with whatever the caller awaits next — reaping
    (and logging) any failure instead of leaving a GC'd "exception never
    retrieved" warning. The best-effort overlap helper behind the h2d
    prefetch call sites; the callable owns its own fallback semantics."""
    fut = asyncio.get_running_loop().run_in_executor(None, fn, *args)

    def _done(t) -> None:
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("blocking task %s died: %r", name or fn, exc)

    fut.add_done_callback(_done)
    return fut
