"""End-to-end request tracing: per-request span timelines across processes.

A request entering the HTTP frontend opens a **root span** whose trace id is
the request id (``Context.id``) — the same id the wire already propagates as
``context_id`` — so spans recorded in *any* process touched by the request
(frontend, router, decode worker, prefill worker) stitch into one trace with
no extra plumbing. On top of that, the data-plane request envelope carries an
optional ``trace`` field ([trace_id, parent_span_id]) so child spans link to
their cross-process parent, not just to the trace.

Pieces:

- :class:`Tracer` — per-process span factory + bounded ring buffer of
  finished spans. ``tracer.span("name")`` is a context manager (sync *and*
  async) that parents itself from :data:`current_span_var`.
- :func:`wire_context` / :func:`extract_wire` — (de)serialize the span
  context for the data-plane control header and queue payloads.
- :class:`StoreSpanSink` — flushes finished spans to the dynstore under
  ``traces/{trace_id}/{span_id}`` on a TTL lease, which is how the frontend's
  ``GET /v1/traces/{request_id}`` endpoint sees spans from other processes
  (and how traces outlive the workers that produced them, until the TTL).
- :func:`to_chrome_trace` — Chrome trace-event JSON (load in Perfetto /
  ``chrome://tracing``): one track per (component, pid), complete events.

Tracing is on by default (``DYN_TRACING=0`` disables; recording a span is two
``perf_counter`` calls and a deque append). Buffer size: ``DYN_TRACE_BUFFER``
(spans, default 4096).

Reference capability: the reference's request-id span fields + OTel-ish
context propagation (lib/runtime/src/logging.rs spans), trimmed to the
in-process flight-recorder shape this repo needs.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

log = logging.getLogger("dynamo_tpu.tracing")

TRACE_STORE_PREFIX = "traces/"


def trace_store_key(trace_id: str, span_id: str) -> str:
    return f"{TRACE_STORE_PREFIX}{trace_id}/{span_id}"


@dataclass
class SpanContext:
    """What travels across process boundaries: which trace, which parent."""

    trace_id: str
    span_id: Optional[str] = None

    def to_wire(self) -> List[Optional[str]]:
        return [self.trace_id, self.span_id]

    @classmethod
    def from_wire(cls, v: Any) -> Optional["SpanContext"]:
        if (isinstance(v, (list, tuple)) and len(v) == 2
                and isinstance(v[0], str)):
            return cls(v[0], v[1] if isinstance(v[1], str) else None)
        return None


current_span_var: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar("dynamo_current_span", default=None)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    component: str
    pid: int
    start: float                 # epoch seconds (cross-process comparable)
    end: float = 0.0
    status: str = "ok"
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "component": self.component, "pid": self.pid,
            "start": self.start, "end": self.end, "status": self.status,
            "attrs": self.attrs,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Span":
        return cls(**{k: d.get(k) for k in (
            "name", "trace_id", "span_id", "parent_id", "component", "pid",
            "start", "end", "status")}, attrs=d.get("attrs") or {})

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# head sampling (fleet-scale pressure relief for the store span sink)
# ---------------------------------------------------------------------------
def sample_rate() -> float:
    """``DYN_TRACE_SAMPLE``: fraction of traces exported to the store
    sink (1.0 = everything, the default). Clamped to [0, 1]; malformed
    values read as 1.0 — misconfiguration must not silence tracing."""
    raw = os.environ.get("DYN_TRACE_SAMPLE", "")
    if not raw:
        return 1.0
    try:
        return min(max(float(raw), 0.0), 1.0)
    except ValueError:
        log.warning("ignoring malformed DYN_TRACE_SAMPLE=%r", raw)
        return 1.0


def trace_sampled(trace_id: str, rate: float) -> bool:
    """Trace-id-consistent head-sampling decision: a deterministic hash of
    the trace id (NOT Python's randomized ``hash``), so every process a
    request touches makes the SAME keep/drop call with no coordination —
    a sampled trace keeps all its spans, an unsampled one keeps none."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int.from_bytes(
        hashlib.sha1(trace_id.encode("utf-8", "replace")).digest()[:8],
        "big")
    return h < rate * 2.0 ** 64


def force_keep(span: "Span") -> bool:
    """Spans head sampling must NEVER drop: anything that finished in a
    non-ok status (errors, deadline expiries, breaker-driven failovers —
    all recorded as ``status="error"``) and fault-injection markers. The
    whole surrounding trace is then retained best-effort (see
    :class:`StoreSpanSink`)."""
    return (span.status != "ok" or span.name.startswith("fault:")
            or bool(span.attrs.get("force_trace")))


class _SpanScope:
    """Context manager (sync and async) around one span: sets
    :data:`current_span_var` for the body, finishes the span on exit,
    marks status=error when the body raises."""

    __slots__ = ("tracer", "span", "_token")

    def __init__(self, tracer: "Tracer", span: Optional["Span"]):
        self.tracer = tracer
        self.span = span
        self._token = None

    def __enter__(self) -> Optional["Span"]:
        if self.span is not None:
            self._token = current_span_var.set(self.span.context())
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.span is None:
            return
        try:
            current_span_var.reset(self._token)
        except ValueError:
            # an abandoned async generator is finalized in a fresh Context
            # (aclose() after a mid-stream disconnect); the token belongs to
            # the serve task's Context — still record the span
            pass
        self.tracer.finish(
            self.span, status="error" if exc_type is not None else "ok")

    async def __aenter__(self) -> Optional["Span"]:
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self.__exit__(exc_type, exc, tb)


class Tracer:
    """Per-process span recorder with a bounded ring of finished spans.

    Thread-safe: the engine thread and the asyncio loop both record.
    Finished spans additionally fan out to registered sinks (e.g.
    :class:`StoreSpanSink`); sink callbacks must be cheap and thread-safe.
    """

    def __init__(self, component: str = "proc",
                 capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        if capacity is None:
            capacity = int(os.environ.get("DYN_TRACE_BUFFER", "4096"))
        if enabled is None:
            enabled = os.environ.get("DYN_TRACING", "1") not in ("0", "false")
        self.component = component
        self.enabled = enabled
        self._spans: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._sinks: List[Callable[[Span], None]] = []

    # -- recording ----------------------------------------------------------
    def start_span(self, name: str, parent: Optional[SpanContext] = None,
                   trace_id: Optional[str] = None,
                   component: Optional[str] = None,
                   start: Optional[float] = None,
                   **attrs: Any) -> Optional[Span]:
        """Open a span. ``parent`` defaults to the ambient context; an
        explicit ``trace_id`` wins over the parent's (used at ingress where
        the request id IS the trace id). Returns None when disabled."""
        if not self.enabled:
            return None
        if parent is None:
            parent = current_span_var.get()
        tid = trace_id or (parent.trace_id if parent else None) \
            or uuid.uuid4().hex
        return Span(
            name=name, trace_id=tid, span_id=_new_span_id(),
            parent_id=parent.span_id if parent else None,
            component=component or self.component, pid=os.getpid(),
            start=time.time() if start is None else start, attrs=attrs)

    def finish(self, span: Optional[Span], status: str = "ok") -> None:
        if span is None or not self.enabled:
            return
        if not span.end:
            span.end = time.time()
        if status != "ok":
            span.status = status
        with self._lock:
            self._spans.append(span)
        for sink in self._sinks:
            try:
                sink(span)
            # dynalint: ok(swallowed-exception) a broken sink must never
            # break the request path; this runs per finished span, and the
            # store sink has its own retrying flush loop that does log
            except Exception:
                pass

    def span(self, name: str, **kw: Any) -> _SpanScope:
        """``with tracer.span("stage"): ...`` / ``async with ...`` sugar."""
        return _SpanScope(self, self.start_span(name, **kw))

    def record(self, name: str, start: float, end: float,
               parent: Optional[SpanContext] = None,
               trace_id: Optional[str] = None,
               component: Optional[str] = None, status: str = "ok",
               **attrs: Any) -> Optional[Span]:
        """Record an already-elapsed interval (e.g. queue wait measured from
        a timestamp stamped in another process)."""
        s = self.start_span(name, parent=parent, trace_id=trace_id,
                            component=component, start=start, **attrs)
        if s is not None:
            s.end = end
            self.finish(s, status=status)
        return s

    def add_sink(self, sink: Callable[[Span], None]) -> None:
        self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[Span], None]) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)

    # -- queries ------------------------------------------------------------
    def spans_for(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]

    def recent_trace_ids(self, limit: int = 50) -> List[str]:
        """Most-recent-first unique trace ids in the ring."""
        seen: Dict[str, None] = {}
        with self._lock:
            snapshot = list(self._spans)
        for s in reversed(snapshot):
            if s.trace_id not in seen:
                seen[s.trace_id] = None
            if len(seen) >= limit:
                break
        return list(seen)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# ---------------------------------------------------------------------------
# process-global tracer
# ---------------------------------------------------------------------------
_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def configure(component: Optional[str] = None,
              capacity: Optional[int] = None,
              enabled: Optional[bool] = None) -> Tracer:
    """Name this process's tracer (e.g. "http", "decode_worker"). Keeps the
    existing ring buffer when only renaming."""
    t = get_tracer()
    if component is not None:
        t.component = component
    if enabled is not None:
        t.enabled = enabled
    if capacity is not None:
        with t._lock:
            t._spans = deque(t._spans, maxlen=max(1, capacity))
    return t


@contextlib.contextmanager
def current_span_var_scope(ctx: Optional[SpanContext]):
    """Temporarily make ``ctx`` the ambient span context."""
    token = current_span_var.set(ctx)
    try:
        yield
    finally:
        current_span_var.reset(token)


# ---------------------------------------------------------------------------
# wire propagation
# ---------------------------------------------------------------------------
def wire_context() -> Optional[List[Optional[str]]]:
    """Current span context as the compact wire form, or None."""
    cur = current_span_var.get()
    return cur.to_wire() if cur is not None else None


def extract_wire(v: Any, default_trace_id: Optional[str] = None
                 ) -> Optional[SpanContext]:
    """Span context from a wire field; falls back to a parentless context on
    ``default_trace_id`` (the request id) so planes that drop the trace field
    (the native C data plane) still stitch spans into the right trace."""
    ctx = SpanContext.from_wire(v)
    if ctx is None and default_trace_id:
        ctx = SpanContext(default_trace_id, None)
    return ctx


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
def merge_spans(*groups: List[Span]) -> List[Span]:
    """Merge span lists (local ring + store fetch), dedupe by span id,
    order by start time."""
    by_id: Dict[str, Span] = {}
    for g in groups:
        for s in g:
            by_id.setdefault(s.span_id, s)
    return sorted(by_id.values(), key=lambda s: (s.start, s.end))


def to_chrome_trace(spans: List[Span]) -> Dict[str, Any]:
    """Chrome trace-event JSON: complete ("X") events, one pid per
    (component, os pid) so Perfetto renders one track per process."""
    procs: Dict[Tuple[str, int], int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        key = (s.component, s.pid)
        if key not in procs:
            procs[key] = len(procs) + 1
            events.append({"name": "process_name", "ph": "M",
                           "pid": procs[key], "tid": 0,
                           "args": {"name": f"{s.component} (pid {s.pid})"}})
    for s in spans:
        events.append({
            "name": s.name, "cat": "dynamo", "ph": "X",
            "ts": round(s.start * 1e6, 3),
            "dur": round(s.duration * 1e6, 3),
            "pid": procs[(s.component, s.pid)], "tid": 0,
            "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                     "parent_id": s.parent_id, "status": s.status,
                     **s.attrs},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# cross-process span export over the dynstore
# ---------------------------------------------------------------------------
class StoreSpanSink:
    """Batches finished spans and writes them to the store under
    ``traces/{trace_id}/{span_id}``, bound to a fresh no-keepalive TTL lease
    per flush — traces expire after ``ttl`` seconds instead of accumulating,
    and survive the producing worker's death until then.

    Fleet-safe: ``sample`` (default ``DYN_TRACE_SAMPLE``) applies
    trace-id-consistent **head sampling** to what reaches the store —
    at 1000 workers an unsampled span plane is a write-rate DDoS on the
    coordination store. Error/deadline/breaker spans (:func:`force_keep`)
    are exported regardless, and force-retain the rest of their trace:
    spans of that trace still in the local ring are retro-enqueued and
    later spans of it are kept, so ``GET /v1/traces/{id}`` shows the whole
    picture for every failed request. Sampled-out spans stay in the local
    ring (``dyn_spans_sampled_out_total`` counts them); the retain-on-
    outage buffer is bounded drop-oldest with ``dyn_spans_dropped_total``
    counting evictions."""

    def __init__(self, store, ttl: float = 600.0,
                 flush_interval: float = 0.25, max_batch: int = 256,
                 max_pending: int = 8192, sample: Optional[float] = None):
        self.store = store
        self.ttl = ttl
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self.sample = sample_rate() if sample is None else \
            min(max(float(sample), 0.0), 1.0)
        # bounded, drop-oldest: a store outage must not grow memory forever
        self._pending: deque = deque(maxlen=max_pending)
        # traces force-retained by an error span (bounded FIFO of ids)
        self._forced: Set[str] = set()
        self._forced_order: deque = deque()
        self._task = None
        self._tracer: Optional[Tracer] = None
        self._loop = None
        self._lease: Optional[int] = None
        self._lease_born = 0.0

    FORCED_LIMIT = 1024   # remembered force-retained trace ids

    async def start(self, tracer: Optional[Tracer] = None) -> "StoreSpanSink":
        import asyncio

        self._loop = asyncio.get_running_loop()
        # NOT `tracer or get_tracer()`: Tracer defines __len__, so a
        # tracer with zero recorded spans is falsy and would silently
        # bind the sink to the process-global tracer instead
        self._tracer = tracer if tracer is not None else get_tracer()
        self._tracer.add_sink(self._on_finish)
        self._task = asyncio.create_task(self._flush_loop())
        return self

    async def stop(self) -> None:
        import asyncio

        if self._tracer is not None:
            self._tracer.remove_sink(self._on_finish)
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                if not self._task.cancelled():
                    raise   # OUR task was cancelled, not the flush loop
            # dynalint: ok(swallowed-exception) reaping our own cancelled
            # flush loop; per-flush errors were logged as they happened
            except Exception:
                pass
        # final drain: flush() caps at max_batch per call, so loop until
        # empty — short-lived runs must not lose their tail of spans
        while await self.flush():
            pass

    def _on_finish(self, span: Span) -> None:
        # may fire on the engine thread: deque.append is atomic, the flush
        # loop drains from the asyncio side
        from .prometheus import stage_metrics

        if not trace_sampled(span.trace_id, self.sample) \
                and span.trace_id not in self._forced:
            if not force_keep(span):
                stage_metrics().spans_sampled_out.inc()
                return
            # an error span in an unsampled trace: retain the WHOLE trace
            # from here on, and retro-enqueue what the local ring still
            # holds of it (store writes are keyed by span id — re-sends
            # after a later error are idempotent overwrites, not dupes)
            self._force_trace(span.trace_id, exclude=span.span_id)
        self._enqueue(span)

    def force_trace(self, trace_id: str) -> None:
        """Retro-export ``trace_id`` regardless of the sampling decision:
        spans of it still in the local ring are enqueued now, later ones
        are force-retained. The incident plane (obs/incidents.py) calls
        this so a bundle's trace is complete even at 1% head sampling."""
        self._force_trace(trace_id)

    def _force_trace(self, trace_id: str, exclude: str = "") -> None:
        self._forced.add(trace_id)
        self._forced_order.append(trace_id)
        while len(self._forced_order) > self.FORCED_LIMIT:
            self._forced.discard(self._forced_order.popleft())
        if self._tracer is not None:
            for prior in self._tracer.spans_for(trace_id):
                if prior.span_id != exclude:
                    self._enqueue(prior)

    def _enqueue(self, span: Span) -> None:
        from .prometheus import stage_metrics

        if self._pending.maxlen is not None \
                and len(self._pending) >= self._pending.maxlen:
            # deque drop-oldest is about to evict: a store outage has
            # outlasted the retain buffer — count the loss
            stage_metrics().spans_dropped.inc()
        self._pending.append(span)

    async def flush(self) -> int:
        """Write everything pending; returns the number of spans written."""
        if not self._pending:
            return 0
        # one no-keepalive lease rotated at ttl/2 (not one per flush —
        # steady streaming flushes every interval and would otherwise pile
        # up ~ttl/interval live leases per worker in the store). Spans ride
        # a lease at most ttl/2 old, so they expire within [ttl/2, ttl].
        # Granted BEFORE popping the batch: a failed grant must not cost
        # spans.
        now = time.monotonic()
        if self._lease is None or now - self._lease_born > self.ttl / 2:
            # unbound: exported spans must survive the producing worker's
            # death until their TTL — that is when they matter most
            self._lease = await self.store.lease_grant(ttl=self.ttl,
                                                       auto_keepalive=False,
                                                       bind=False)
            self._lease_born = now
        lease = self._lease
        batch: List[Span] = []
        while self._pending and len(batch) < self.max_batch:
            batch.append(self._pending.popleft())
        if not batch:
            return 0
        written = 0
        try:
            for s in batch:
                await self.store.put(trace_store_key(s.trace_id, s.span_id),
                                     json.dumps(s.to_dict()).encode(),
                                     lease=lease)
                written += 1
        except BaseException as e:
            # transient store failure: put the unwritten tail back at the
            # front (original order) so the next flush retries it. If new
            # spans refilled the deque meanwhile, extendleft on a full
            # deque would silently evict the NEWEST from the right —
            # inverted policy, uncounted loss. Keep drop-oldest instead:
            # shed the head of the tail (the oldest spans overall) and
            # count them.
            from .prometheus import stage_metrics

            tail = batch[written:]
            if self._pending.maxlen is not None:
                overflow = len(tail) - (self._pending.maxlen
                                        - len(self._pending))
                if overflow > 0:
                    stage_metrics().spans_dropped.inc(amount=overflow)
                    tail = tail[overflow:]
            self._pending.extendleft(reversed(tail))
            # a restarted (empty) store no longer knows our no-keepalive
            # lease: drop it so the next flush re-grants instead of
            # stalling spans until the ttl/2 rotation
            if getattr(e, "code", "") in ("lease_not_found", "conn_lost"):
                self._lease = None
            raise
        return written

    async def _flush_loop(self) -> None:
        import asyncio

        while True:
            try:
                await self.flush()
            except asyncio.CancelledError:
                raise
            except Exception:
                # store hiccups must not kill the process; spans are
                # retained and the next tick retries
                log.debug("span flush failed; retrying next tick",
                          exc_info=True)
            await asyncio.sleep(self.flush_interval)


async def fetch_trace_spans(store, trace_id: str) -> List[Span]:
    """All spans of one trace published to the store by any process."""
    out: List[Span] = []
    for _key, value in await store.get_prefix(
            f"{TRACE_STORE_PREFIX}{trace_id}/"):
        try:
            out.append(Span.from_dict(json.loads(value.decode())))
        except Exception:
            # one corrupt span record must not hide the rest of the trace
            log.debug("skipping undecodable span under trace %s",
                      trace_id, exc_info=True)
            continue
    return out
