"""Runtime configuration layering: defaults <- DYN_* environment <- CLI.

The reference layers figment defaults under ``DYN_*`` environment variables
under explicit flags (lib/runtime/src/config.rs:26-176). Here the same
precedence is expressed through argparse: every runtime flag's DEFAULT is
resolved from the environment, so a flag given on the command line always
wins, and an env var beats the built-in default.

Lookup order for a flag ``--port`` of binary ``dynamo-http``:

1. ``DYN_HTTP_PORT``   (binary-scoped: DYN_<PROG>_<FLAG>; lets two binaries
   on one host get different values for a same-named flag)
2. ``DYN_PORT``        (global: DYN_<FLAG>; e.g. DYN_STORE applies to every
   binary at once)
3. the built-in default.

A malformed env value (e.g. DYN_PORT=abc for an int flag, or a value outside
the flag's ``choices``) is logged and ignored rather than crashing startup.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Any, Optional

log = logging.getLogger("dynamo_tpu.config")


def _norm(s: str) -> str:
    return s.replace("-", "_").upper()


def env_name(flag: str, prog: Optional[str] = None) -> str:
    base = _norm(flag.lstrip("-"))
    if prog:
        p = _norm(prog)
        if p.startswith("DYNAMO_"):
            p = p[len("DYNAMO_"):]
        return f"DYN_{p}_{base}"
    return "DYN_" + base


def env_default(flag: str, default: Any = None, cast: Optional[type] = None,
                prog: Optional[str] = None, choices=None) -> Any:
    """The default for ``flag``: the binary-scoped then global DYN_* env
    value when set, else ``default``. ``cast`` converts the env string."""
    raw = None
    for name in ((env_name(flag, prog),) if prog else ()) + (env_name(flag),):
        raw = os.environ.get(name)
        if raw is not None:
            break
    if raw is None:
        return default
    if cast is None and default is not None:
        cast = type(default)
    try:
        if cast is bool:
            val = raw.lower() not in ("", "0", "false", "no")
        else:
            val = cast(raw) if cast else raw
    except (TypeError, ValueError):
        log.warning("ignoring malformed %s=%r for flag %s", name, raw, flag)
        return default
    if choices is not None and val not in choices:
        log.warning("ignoring %s=%r: not one of %s", name, raw, list(choices))
        return default
    return val


class EnvDefaultsParser(argparse.ArgumentParser):
    """ArgumentParser whose ``add_argument`` resolves defaults through the
    DYN_* environment, giving the reference's defaults<-env<-flags layering
    to every binary that uses it."""

    def add_argument(self, *names, **kw):  # type: ignore[override]
        flag = next((n for n in names if n.startswith("--")), None)
        if flag is not None and "default" in kw and kw.get("action") not in (
                "store_true", "store_false", "append"):
            kw["default"] = env_default(flag, kw["default"], kw.get("type"),
                                        prog=self.prog,
                                        choices=kw.get("choices"))
        elif flag is not None and kw.get("action") == "store_true":
            if env_default(flag, False, bool, prog=self.prog):
                kw["default"] = True
        return super().add_argument(*names, **kw)
