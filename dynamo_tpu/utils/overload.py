"""Overload control: admission, bounded queues, priority shedding, brownout.

Under sustained overload an uncontrolled serving plane exhibits congestion
collapse: every request is accepted, queues grow without bound, and each
request burns its full end-to-end deadline before dying as a 504 — goodput
goes to zero exactly when load peaks. This module is the admit/reject
boundary that prevents that:

- **Admission control** (:class:`AdmissionController`): token-bucket rate
  limit plus in-flight concurrency caps at HTTP ingress, answered with an
  immediate 429 + ``Retry-After`` — shed work costs milliseconds, not a
  deadline.
- **Priority classes**: every request carries ``interactive`` or ``batch``
  (the ``x-priority`` header, propagated on the wire envelope). Shedding
  and queue ordering strictly prefer interactive — batch absorbs the pain
  first at every decision point.
- **Bounded stage queues with predictive shedding**
  (:class:`PriorityGate`, plus the bounds in ``llm/disagg.PrefillQueue``):
  hard depth caps, and reject-at-enqueue when the estimated wait
  (queue depth x observed per-item service time) already exceeds the
  request's remaining deadline.
- **SLO-burn-driven brownout** (:class:`BrownoutController`): a small
  controller watches the ``utils/slo.py`` burn rate and steps through
  degradation levels — shed batch, cap ``max_tokens``, disable speculative
  decoding, shed everything — publishing the active level to the store so
  every frontend/router applies it fleet-wide.

Shed-vs-deadline semantics: a *shed* (429) is the plane refusing work it
predicts it cannot finish — it must be decided in milliseconds and costs
the client only a retry. A *deadline expiry* (504) is admitted work that
ran out of budget mid-pipeline. A healthy overloaded plane converts
would-be 504s into fast 429s; ``scripts/overload_soak.py`` asserts exactly
that conversion.

Grounded in FlowKV's load-aware-scheduling argument (PAPERS.md) extended
to the admit/reject boundary.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..runtime.engine import EngineError
from .knobs import env_float as _env_float

log = logging.getLogger("dynamo_tpu.overload")

# ---------------------------------------------------------------------------
# priority classes
# ---------------------------------------------------------------------------
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"
PRIORITIES = (PRIORITY_INTERACTIVE, PRIORITY_BATCH)
PRIORITY_HEADER = "x-priority"


def parse_priority(raw: Optional[str]) -> str:
    """Header value -> priority class. Absent/empty => interactive (the
    protective default: unaware clients must not be shed first); an unknown
    value raises ValueError (the client's typo — a 400, not a silent
    demotion to batch)."""
    if not raw:
        return PRIORITY_INTERACTIVE
    p = raw.strip().lower()
    if p not in PRIORITIES:
        raise ValueError(
            f"{PRIORITY_HEADER}: {raw!r} (expected one of {PRIORITIES})")
    return p


# ---------------------------------------------------------------------------
# tenancy: who is asking, and on what terms
# ---------------------------------------------------------------------------
TENANT_HEADER = "x-tenant"
DEFAULT_TENANT = "default"
_TENANT_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def parse_tenant(raw: Optional[str]) -> str:
    """``x-tenant`` header -> tenant id. Absent/empty => ``default``
    (tenancy-unaware clients share one bucket); a malformed id raises
    ValueError (400 — a typo'd tenant silently pooled into ``default``
    would dodge its quota)."""
    if not raw:
        return DEFAULT_TENANT
    t = raw.strip()
    if not t or len(t) > 64 or not set(t) <= _TENANT_CHARS:
        raise ValueError(
            f"{TENANT_HEADER}: {raw!r} (expected 1-64 chars of "
            f"[A-Za-z0-9._-])")
    return t


class OverloadError(EngineError):
    """Typed shed: the plane refused work it predicts it cannot finish.
    Maps to HTTP 429 with ``Retry-After``; ``stage`` names the decision
    point, ``reason`` the rule that fired."""

    def __init__(self, message: str, stage: str, reason: str,
                 retry_after: Optional[float] = None, code: int = 429):
        super().__init__(message, code, stage=stage, reason=reason,
                         retry_after=retry_after)


# ---------------------------------------------------------------------------
# token bucket + admission control (HTTP ingress)
# ---------------------------------------------------------------------------
class TokenBucket:
    """Classic token bucket with an injectable clock (tests use a virtual
    one). ``floor`` lets a caller class refuse to drain the bucket below a
    reserve — batch traffic keeps ``reserve`` tokens standing for
    interactive arrivals."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self.clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now

    def take(self, n: float = 1.0, floor: float = 0.0) -> bool:
        self._refill()
        if self.tokens - n >= floor - 1e-12:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0, floor: float = 0.0) -> float:
        """Seconds until ``take(n, floor)`` could succeed at current drain."""
        self._refill()
        deficit = (floor + n) - self.tokens
        if deficit <= 0 or self.rate <= 0:
            return 1.0
        return deficit / self.rate


@dataclass
class AdmissionConfig:
    """``DYN_ADMIT_*`` knobs. Zero/unset disables the corresponding cap —
    a frontend with no knobs set admits everything (legacy behavior)."""

    rps: float = 0.0            # token-bucket refill rate (req/s); 0 = off
    burst: float = 0.0          # bucket size; default 2 x rps
    concurrency: int = 0        # max in-flight requests; 0 = off
    queue: int = 0              # extra in-flight headroom granted ONLY to
                                # interactive traffic (batch rejects at
                                # ``concurrency``); default concurrency//2
    batch_reserve: float = 0.25  # fraction of burst batch may not drain
    # byte-honest KV dimension: in-flight requests are additionally
    # priced in estimated KV bytes (tokens x kv_token_bytes) against a
    # kv_bytes budget, so ONE 128k-context request consumes its true
    # share of the admission envelope instead of one concurrency slot.
    # Both must be > 0 to arm the dimension.
    kv_bytes: float = 0.0       # in-flight KV byte budget; 0 = off
    kv_token_bytes: float = 0.0  # per-token KV price, bytes

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None
                 ) -> "AdmissionConfig":
        rps = _env_float("DYN_ADMIT_RPS", 0.0, env)
        burst = _env_float("DYN_ADMIT_BURST", 0.0, env) or 2.0 * rps
        conc = int(_env_float("DYN_ADMIT_CONCURRENCY", 0, env))
        queue = int(_env_float("DYN_ADMIT_QUEUE", -1, env))
        if queue < 0:
            queue = conc // 2
        reserve = _env_float("DYN_ADMIT_BATCH_RESERVE", 0.25, env)
        return cls(rps=rps, burst=burst, concurrency=conc, queue=queue,
                   batch_reserve=min(max(reserve, 0.0), 1.0),
                   kv_bytes=_env_float("DYN_ADMIT_KV_BYTES", 0.0, env),
                   kv_token_bytes=_env_float("DYN_ADMIT_KV_TOKEN_BYTES",
                                             0.0, env))


class AdmissionController:
    """Ingress gatekeeper: rate (token bucket) + in-flight concurrency.

    ``try_admit`` either reserves an in-flight slot (caller MUST
    ``release()`` on every exit path) or returns an :class:`OverloadError`
    describing the shed — it never raises, so the HTTP layer stays in
    control of the response. Batch hits both caps earlier than interactive:
    it cannot drain the token bucket below ``batch_reserve x burst``, and
    it gets no share of the ``queue`` headroom above ``concurrency``."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or AdmissionConfig()
        c = self.config
        self.bucket = (TokenBucket(c.rps, max(c.burst, 1.0), clock)
                       if c.rps > 0 else None)
        self.inflight = 0
        self.kv_inflight_bytes = 0.0

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None
                 ) -> "AdmissionController":
        return cls(AdmissionConfig.from_env(env))

    @property
    def enabled(self) -> bool:
        return self.bucket is not None or self.config.concurrency > 0

    def _metrics(self):
        from .prometheus import stage_metrics

        return stage_metrics()

    def _reject(self, reason: str, priority: str,
                retry_after: float) -> OverloadError:
        self._metrics().admission_rejects.inc(reason, priority)
        return OverloadError(
            f"admission rejected ({reason}; priority={priority}): "
            f"server is at capacity, retry after {retry_after:.2f}s",
            stage="admission", reason=reason, retry_after=retry_after)

    def try_admit(self, priority: str = PRIORITY_INTERACTIVE
                  ) -> Optional[OverloadError]:
        c = self.config
        # concurrency BEFORE the bucket: a request the in-flight cap is
        # going to reject must not consume a rate token, or the retries it
        # provokes drain the budget and admittable requests later eat
        # spurious rate_limit 429s
        if c.concurrency > 0:
            limit = c.concurrency
            if priority != PRIORITY_BATCH:
                limit += c.queue
            if self.inflight >= limit:
                return self._reject("concurrency", priority, 1.0)
        if self.bucket is not None:
            floor = c.batch_reserve * self.bucket.burst \
                if priority == PRIORITY_BATCH else 0.0
            if not self.bucket.take(1.0, floor=floor):
                return self._reject("rate_limit", priority,
                                    self.bucket.retry_after(1.0, floor))
        self.inflight += 1
        self._metrics().admission_depth.set(value=self.inflight)
        return None

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)
        self._metrics().admission_depth.set(value=self.inflight)

    # ------------------------------------------------------------------
    # byte-honest KV dimension (second gate, once token counts exist)
    # ------------------------------------------------------------------
    @property
    def kv_enabled(self) -> bool:
        c = self.config
        return c.kv_bytes > 0 and c.kv_token_bytes > 0

    def price_kv(self, est_tokens: float) -> float:
        """A request's KV price in bytes (0 when the dimension is off)."""
        return (est_tokens * self.config.kv_token_bytes
                if self.kv_enabled else 0.0)

    def try_reserve_kv(self, kv_bytes: float,
                       priority: str = PRIORITY_INTERACTIVE
                       ) -> Optional[OverloadError]:
        """Reserve ``kv_bytes`` of the in-flight KV budget or explain the
        shed. Runs AFTER the header-stage gate (token counts only exist
        once the body is read); the caller must :meth:`release_kv` the
        same amount on every exit path after a None return. A request
        larger than the whole budget is a 400-shaped client error, not a
        retryable 429 — retrying cannot ever fit it."""
        if kv_bytes <= 0 or not self.kv_enabled:
            return None
        c = self.config
        if kv_bytes > c.kv_bytes:
            self._metrics().admission_rejects.inc("kv_bytes", priority)
            return OverloadError(
                f"request KV working set of {kv_bytes / 1e6:.0f} MB "
                f"exceeds the whole admission budget "
                f"({c.kv_bytes / 1e6:.0f} MB)", stage="admission",
                reason="kv_bytes", code=400)
        if self.kv_inflight_bytes + kv_bytes > c.kv_bytes:
            return self._reject("kv_bytes", priority, 1.0)
        self.kv_inflight_bytes += kv_bytes
        self._metrics().admission_kv_bytes.set(
            value=self.kv_inflight_bytes)
        return None

    def release_kv(self, kv_bytes: float) -> None:
        if kv_bytes <= 0 or not self.kv_enabled:
            return
        self.kv_inflight_bytes = max(0.0, self.kv_inflight_bytes - kv_bytes)
        self._metrics().admission_kv_bytes.set(
            value=self.kv_inflight_bytes)


# ---------------------------------------------------------------------------
# per-tenant quotas: isolation, not capacity management
# ---------------------------------------------------------------------------
@dataclass
class TenantQuota:
    """One tenant's ingress allowance. Zero fields are *uncapped* (a
    tenant with only an rps quota has unlimited concurrency and vice
    versa); a tenant with no quota record at all is ungoverned — only the
    global admission caps apply to it."""

    rps: float = 0.0            # token-bucket refill (req/s); 0 = uncapped
    burst: float = 0.0          # bucket size; default 2 x rps
    concurrency: int = 0        # max in-flight; 0 = uncapped

    @property
    def enabled(self) -> bool:
        return self.rps > 0 or self.concurrency > 0

    def to_dict(self) -> Dict[str, Any]:
        return {"rps": self.rps, "burst": self.burst,
                "concurrency": self.concurrency}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TenantQuota":
        return cls(rps=float(d.get("rps", 0.0)),
                   burst=float(d.get("burst", 0.0)),
                   concurrency=int(d.get("concurrency", 0)))


def tenant_quotas_from_env(env: Optional[Dict[str, str]] = None
                           ) -> Dict[str, TenantQuota]:
    """``DYN_TENANT_QUOTAS`` — a JSON object mapping tenant id to
    ``{"rps": .., "burst": .., "concurrency": ..}``. A malformed table is
    the operator's typo: logged and ignored (never inflicted on clients
    as spurious 429s)."""
    import os

    raw = (os.environ if env is None else env).get("DYN_TENANT_QUOTAS")
    if not raw:
        return {}
    try:
        table = json.loads(raw)
        return {str(t): TenantQuota.from_dict(q)
                for t, q in table.items()}
    except (ValueError, TypeError, AttributeError, json.JSONDecodeError):
        log.warning("ignoring malformed DYN_TENANT_QUOTAS=%r", raw)
        return {}


class TenantAdmission:
    """Per-tenant token buckets + in-flight caps, layered *under* the
    global :class:`AdmissionController` at HTTP ingress.

    A tenant-quota shed is a different beast from an overload shed: it is
    deliberate *isolation* (this tenant exceeded its contract), not a
    capacity signal — so it counts ``dyn_tenant_admission_rejects_total``
    but NOT ``dyn_admission_rejects_total``, keeping the planner's
    rejected-demand scale-up pressure blind to it by design (scaling the
    fleet up must not be how a tenant escapes its quota).

    Metric label cardinality is bounded by construction: only tenants
    present in the quota table get their own label; everyone else is
    ``other`` (tenant ids are client-controlled strings).

    ``set_quotas`` applies live updates (the fleet registry watch feeds
    it) while *preserving* the bucket level of unchanged quotas — a
    registry refresh must not hand every hog a freshly full bucket."""

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.quotas: Dict[str, TenantQuota] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._inflight: Dict[str, int] = {}
        self.set_quotas(quotas or {})

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None
                 ) -> "TenantAdmission":
        return cls(tenant_quotas_from_env(env))

    @property
    def enabled(self) -> bool:
        return any(q.enabled for q in self.quotas.values())

    def label(self, tenant: str) -> str:
        """Bounded-cardinality metric label for ``tenant``: quota-table
        tenants and the built-in default keep their name, every other
        client-controlled string collapses to ``other``."""
        if tenant in self.quotas or tenant == DEFAULT_TENANT:
            return tenant
        return "other"

    def set_quotas(self, quotas: Dict[str, TenantQuota]) -> None:
        for tenant, q in quotas.items():
            old = self.quotas.get(tenant)
            if q.rps > 0 and (old is None or old.rps != q.rps
                              or old.burst != q.burst
                              or tenant not in self._buckets):
                burst = q.burst if q.burst > 0 else 2.0 * q.rps
                self._buckets[tenant] = TokenBucket(
                    q.rps, max(burst, 1.0), clock=self.clock)
            elif q.rps <= 0:
                self._buckets.pop(tenant, None)
        for tenant in list(self._buckets):
            if tenant not in quotas:
                self._buckets.pop(tenant)
        self.quotas = dict(quotas)

    def _reject(self, tenant: str, priority: str, reason: str,
                retry_after: float) -> OverloadError:
        from .prometheus import stage_metrics

        stage_metrics().tenant_rejects.inc(self.label(tenant), reason)
        return OverloadError(
            f"tenant {tenant!r} over quota ({reason}; "
            f"priority={priority}): retry after {retry_after:.2f}s",
            stage="admission", reason=reason, retry_after=retry_after)

    def try_admit(self, tenant: str,
                  priority: str = PRIORITY_INTERACTIVE
                  ) -> Optional[OverloadError]:
        """Reserve a tenant slot or explain the shed. The caller MUST
        :meth:`release` on every exit path after a None return — same
        contract as :class:`AdmissionController`. Unquota'd tenants are
        admitted without bookkeeping (release is a no-op for them)."""
        q = self.quotas.get(tenant)
        if q is None or not q.enabled:
            return None
        held = self._inflight.get(tenant, 0)
        if q.concurrency > 0 and held >= q.concurrency:
            return self._reject(tenant, priority, "tenant_concurrency", 1.0)
        bucket = self._buckets.get(tenant)
        if bucket is not None and not bucket.take(1.0):
            return self._reject(tenant, priority, "tenant_rate",
                                bucket.retry_after(1.0))
        self._inflight[tenant] = held + 1
        from .prometheus import stage_metrics

        stage_metrics().tenant_inflight.set(self.label(tenant),
                                            value=held + 1)
        return None

    def release(self, tenant: str) -> None:
        held = self._inflight.get(tenant)
        if held is None:
            return
        self._inflight[tenant] = max(held - 1, 0)
        from .prometheus import stage_metrics

        stage_metrics().tenant_inflight.set(self.label(tenant),
                                            value=self._inflight[tenant])


def tenant_availability_objective(env: Optional[Dict[str, str]] = None
                                  ) -> Optional[float]:
    """``DYN_TENANT_AVAILABILITY`` — per-tenant good-request fraction
    objective (e.g. 0.99). Unset/invalid = tenant burn not monitored."""
    import os

    raw = (os.environ if env is None else env).get("DYN_TENANT_AVAILABILITY")
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        log.warning("ignoring malformed DYN_TENANT_AVAILABILITY=%r", raw)
        return None
    return v if 0.0 < v < 1.0 else None


def tenant_request_totals(states) -> Dict[str, Tuple[float, float]]:
    """{tenant: (total, bad)} cumulative request counts from the
    ``dyn_tenant_requests_total{tenant,status}`` series frontends
    publish. bad = 5xx (server-fault); 429s are the tenant's own quota
    and 4xx its own input — neither burns the *server's* budget."""
    out: Dict[str, List[float]] = {}
    for _component, dump in states:
        st = dump.get("dyn_tenant_requests_total")
        if not st or st.get("kind") != "counter":
            continue
        labels = list(st.get("labels") or ())
        try:
            t_pos = labels.index("tenant")
            s_pos = labels.index("status")
        except ValueError:
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if len(parts) <= max(t_pos, s_pos):
                continue
            acc = out.setdefault(parts[t_pos], [0.0, 0.0])
            acc[0] += val
            try:
                if int(parts[s_pos]) >= 500:
                    acc[1] += val
            except ValueError:
                pass
    return {t: (v[0], v[1]) for t, v in out.items()}


class TenantBurnTracker:
    """Per-tenant availability error-budget burn over the published
    stage dumps — the tenant-scoped SLO signal the brownout ladder (and
    dyntop) consume. Same cumulative-snapshot-ring recipe as
    ``utils/slo.SloMonitor``, one ring per tenant, worst window wins."""

    def __init__(self, objective: float,
                 windows: Optional[Tuple[float, ...]] = None,
                 clock: Callable[[], float] = time.monotonic):
        from .slo import windows_from_env

        self.objective = objective
        self.budget = max(1.0 - objective, 1e-9)
        self.windows = tuple(windows or windows_from_env())
        self.clock = clock
        self._rings: Dict[str, collections.deque] = {}
        self._last: Dict[str, float] = {}

    def observe(self, states, now: Optional[float] = None
                ) -> Dict[str, float]:
        """{tenant: worst-window burn}; also exports the
        ``dyn_tenant_slo_burn`` gauge per tenant seen."""
        now = self.clock() if now is None else now
        from .prometheus import stage_metrics

        out: Dict[str, float] = {}
        horizon = now - max(self.windows) - 1.0
        for tenant, (total, bad) in tenant_request_totals(states).items():
            ring = self._rings.setdefault(tenant, collections.deque())
            ring.append((now, total, bad))
            while len(ring) > 2 and ring[1][0] < horizon:
                ring.popleft()
            worst = 0.0
            for w in self.windows:
                base_t, base_total, base_bad = ring[0]
                for ts, t_, b_ in ring:
                    if ts <= now - w:
                        base_t, base_total, base_bad = ts, t_, b_
                    else:
                        break
                d_total = total - base_total
                if d_total > 0:
                    worst = max(worst,
                                ((bad - base_bad) / d_total) / self.budget)
            out[tenant] = worst
            stage_metrics().tenant_burn.set(tenant, value=worst)
        self._last = out
        return out

    def worst(self) -> float:
        return max(self._last.values(), default=0.0)


def estimate_request_tokens(oai_req) -> float:
    """Crude ingress-side token estimate for KV-byte pricing: prompt
    characters (exact for the byte tokenizer, a safe overestimate for
    BPE) plus the requested ``max_tokens`` (256 when unset). Runs before
    tokenization, so it is a pricing heuristic, not an accounting truth —
    the engine's paged-admission check re-prices exactly in blocks."""
    chars = 0
    prompt = getattr(oai_req, "prompt", None)
    if isinstance(prompt, str):
        chars = len(prompt)
    elif isinstance(prompt, (list, tuple)):
        chars = sum(len(p) if isinstance(p, str) else 1 for p in prompt)
    for msg in getattr(oai_req, "messages", None) or ():
        content = msg.get("content") if isinstance(msg, dict) else None
        if isinstance(content, str):
            chars += len(content)
        elif isinstance(content, (list, tuple)):
            for part in content:
                if isinstance(part, dict):
                    chars += len(str(part.get("text", "")))
    return float(chars) + float(getattr(oai_req, "max_tokens", None) or 256)


# ---------------------------------------------------------------------------
# predictive shed math
# ---------------------------------------------------------------------------
def predicted_wait(depth: float, service_s: Optional[float],
                   servers: int = 1) -> Optional[float]:
    """Estimated queue wait: ``depth`` items ahead, each costing
    ``service_s`` seconds, drained by ``servers`` parallel consumers. None
    when no service-time observation exists yet (never shed blind)."""
    if service_s is None or service_s <= 0:
        return None
    return depth * service_s / max(servers, 1)


def should_shed(depth: float, service_s: Optional[float],
                remaining_s: Optional[float], servers: int = 1) -> bool:
    """True when the estimated wait alone already exceeds the request's
    remaining deadline budget — the work is doomed; fail it in
    milliseconds instead of letting it burn the deadline in a queue. A
    request with no deadline is never predictively shed (nothing to burn)."""
    if remaining_s is None:
        return False
    wait = predicted_wait(depth, service_s, servers)
    return wait is not None and wait > remaining_s


def histogram_mean(hist) -> Optional[float]:
    """Mean observation of an in-process ``utils.prometheus.Histogram``
    across all its label series (diagnostics helper; the live shed paths
    use their own :class:`ServiceTimeEstimator` EWMAs, which react faster
    than a lifetime-cumulative mean)."""
    st = hist.state()
    total = sum(s.get("total", 0) for s in st.get("series", {}).values())
    if not total:
        return None
    return sum(s.get("sum", 0.0)
               for s in st.get("series", {}).values()) / total


class ServiceTimeEstimator:
    """EWMA of observed per-item service seconds; cheap, process-local,
    and robust to the cold start (None until the first observation)."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self._mean: Optional[float] = None

    def observe(self, seconds: float) -> None:
        if seconds < 0:
            return
        self._mean = seconds if self._mean is None else \
            (1 - self.alpha) * self._mean + self.alpha * seconds

    def mean(self) -> Optional[float]:
        return self._mean


# ---------------------------------------------------------------------------
# worker ingress: bounded slot gate with strict priority wakeup
# ---------------------------------------------------------------------------
class PriorityGate:
    """Counted engine slots with bounded, priority-ordered wait queues.

    ``acquire`` hands out a slot immediately when one is free; otherwise
    the caller waits in its priority's queue — ``release`` ALWAYS wakes an
    interactive waiter before any batch waiter, so batch traffic absorbs
    queueing pain first. Before waiting, two shed rules run:

    - hard depth bound per priority (batch's bound is lower), and
    - predictive shed: estimated wait (position x observed service time /
      slots) already exceeds the remaining deadline.

    Both raise :class:`OverloadError` (stage ``worker_queue``) in
    microseconds and count ``dyn_queue_shed_total``.
    """

    def __init__(self, slots: int, max_queue: int = 0,
                 max_queue_batch: Optional[int] = None,
                 stage: str = "worker_queue"):
        self.slots = max(int(slots), 1)
        self.free = self.slots
        self.max_queue = int(max_queue)
        self.max_queue_batch = (self.max_queue // 2
                                if max_queue_batch is None
                                else int(max_queue_batch))
        self.stage = stage
        self.service = ServiceTimeEstimator()
        self._waiters: Dict[str, collections.deque] = {
            p: collections.deque() for p in PRIORITIES}

    @property
    def waiting(self) -> int:
        return sum(len(q) for q in self._waiters.values())

    def _shed(self, reason: str, priority: str,
              retry_after: float = 1.0) -> OverloadError:
        from .prometheus import stage_metrics

        stage_metrics().queue_shed.inc(self.stage)
        return OverloadError(
            f"{self.stage} shed ({reason}; priority={priority}): "
            f"{self.waiting} waiting on {self.slots} slots",
            stage=self.stage, reason=reason, retry_after=retry_after)

    def check(self, priority: str,
              deadline: Optional[float]) -> Optional[OverloadError]:
        """The shed decision alone (no slot state change): depth bound,
        then predictive wait vs the remaining deadline."""
        if self.free > 0 and self.waiting == 0:
            return None
        # batch's bound is lower but counts TOTAL waiters: interactive
        # backlog alone is enough to close the door on batch — strictly
        # prefer interactive at every decision point
        bound = (self.max_queue_batch if priority == PRIORITY_BATCH
                 else self.max_queue)
        if self.waiting >= bound:
            svc = self.service.mean() or 0.0
            return self._shed("queue_full", priority,
                              retry_after=max(svc, 0.05))
        from ..runtime import deadline as dl

        remaining = dl.remaining(deadline)
        # this request's position in line: everyone already waiting (plus
        # itself) over the parallel slots
        if should_shed(self.waiting + 1, self.service.mean(), remaining,
                       servers=self.slots):
            wait = predicted_wait(self.waiting + 1, self.service.mean(),
                                  self.slots) or 1.0
            return self._shed("predicted_late", priority,
                              retry_after=wait)
        return None

    async def acquire(self, priority: str,
                      deadline: Optional[float]) -> None:
        """Take a slot, waiting (deadline-bounded) in priority order.
        Raises :class:`OverloadError` on shed, ``DeadlineExceeded`` when
        the deadline fires while queued."""
        rej = self.check(priority, deadline)
        if rej is not None:
            raise rej
        if self.free > 0 and self.waiting == 0:
            self.free -= 1
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters[priority].append(fut)
        from ..runtime import deadline as dl

        try:
            await dl.wait_for(fut, deadline, self.stage)
        except BaseException:
            if fut.done() and not fut.cancelled():
                # the slot handoff raced the expiry/cancel: give it back
                self._release_slot()
            else:
                try:
                    self._waiters[priority].remove(fut)
                except ValueError:
                    pass
                fut.cancel()
            raise

    def _release_slot(self) -> None:
        for p in PRIORITIES:            # strict order: interactive first
            q = self._waiters[p]
            while q:
                fut = q.popleft()
                if not fut.done():
                    fut.set_result(None)
                    return
        self.free = min(self.free + 1, self.slots)

    def release(self, service_s: Optional[float] = None) -> None:
        if service_s is not None:
            self.service.observe(service_s)
            from .prometheus import stage_metrics

            stage_metrics().stage_service.observe("worker", value=service_s)
        self._release_slot()


class SlotGatedEngine:
    """AsyncEngine wrapper enforcing a :class:`PriorityGate` around every
    ``generate`` stream — the worker-ingress bound of the overload plane."""

    def __init__(self, engine, gate: PriorityGate):
        self.engine = engine
        self.gate = gate

    async def generate(self, request, context):
        await self.gate.acquire(getattr(context, "priority",
                                        PRIORITY_INTERACTIVE),
                                getattr(context, "deadline", None))
        started = time.monotonic()
        try:
            async for item in self.engine.generate(request, context):
                yield item
        finally:
            self.gate.release(time.monotonic() - started)


def gate_from_env(env: Optional[Dict[str, str]] = None
                  ) -> Optional[PriorityGate]:
    """``DYN_WORKER_SLOTS`` (0/unset = no gate), ``DYN_WORKER_QUEUE_DEPTH``
    (default 2 x slots), ``DYN_WORKER_BATCH_QUEUE_DEPTH`` (default half the
    interactive bound)."""
    slots = int(_env_float("DYN_WORKER_SLOTS", 0, env))
    if slots <= 0:
        return None
    max_q = int(_env_float("DYN_WORKER_QUEUE_DEPTH", 2 * slots, env))
    batch_q = int(_env_float("DYN_WORKER_BATCH_QUEUE_DEPTH", -1, env))
    return PriorityGate(slots, max_queue=max_q,
                        max_queue_batch=None if batch_q < 0 else batch_q)


# ---------------------------------------------------------------------------
# SLO-burn-driven brownout
# ---------------------------------------------------------------------------
LEVEL_NORMAL = 0          # full service
LEVEL_SHED_BATCH = 1      # batch traffic rejected at ingress
LEVEL_CAP_TOKENS = 2      # + max_tokens capped (shrink work per request)
LEVEL_NO_SPEC = 3         # + speculative decoding's extra programs off
LEVEL_SHED_ALL = 4        # all new work rejected (survival mode)
MAX_LEVEL = LEVEL_SHED_ALL

LEVEL_NAMES = {
    LEVEL_NORMAL: "normal",
    LEVEL_SHED_BATCH: "shed_batch",
    LEVEL_CAP_TOKENS: "cap_tokens",
    LEVEL_NO_SPEC: "no_spec",
    LEVEL_SHED_ALL: "shed_all",
}


def sheds_batch(level: int) -> bool:
    return level >= LEVEL_SHED_BATCH


def max_tokens_cap(level: int,
                   env: Optional[Dict[str, str]] = None) -> Optional[int]:
    """The brownout ``max_tokens`` ceiling (``DYN_BROWNOUT_MAX_TOKENS``,
    default 256) — None below the cap level."""
    if level < LEVEL_CAP_TOKENS:
        return None
    return int(_env_float("DYN_BROWNOUT_MAX_TOKENS", 256, env))


def disables_spec(level: int) -> bool:
    return level >= LEVEL_NO_SPEC


def sheds_all(level: int) -> bool:
    return level >= LEVEL_SHED_ALL


def brownout_reject(priority: str, level: int) -> Optional[OverloadError]:
    """The ingress brownout decision: shed everything at L4+, shed batch
    at L1+. Counted as admission rejects (it IS the admission boundary)."""
    if sheds_all(level):
        reason = "brownout_shed_all"
    elif priority == PRIORITY_BATCH and sheds_batch(level):
        reason = "brownout_batch"
    else:
        return None
    from .prometheus import stage_metrics

    stage_metrics().admission_rejects.inc(reason, priority)
    return OverloadError(
        f"brownout level {level} ({LEVEL_NAMES.get(level, '?')}): "
        f"{priority} traffic is being shed until the SLO burn recovers",
        stage="admission", reason=reason, retry_after=5.0)


class BrownoutController:
    """Steps the degradation level on the SLO burn rate, with hysteresis.

    - step UP one level when burn >= ``up_burn`` and ``dwell_up`` seconds
      have passed since the last change (the dwell lets the previous
      level's relief land before escalating);
    - step DOWN one level only when burn <= ``down_burn`` (strictly below
      the up threshold — the hysteresis band) for ``dwell_down`` seconds.

    Deterministic and clock-injected; the store publication / gauge export
    live on :class:`BrownoutMonitor` so this core is unit-testable with a
    virtual clock."""

    def __init__(self, up_burn: float = 2.0, down_burn: float = 0.75,
                 dwell_up: float = 5.0, dwell_down: float = 30.0,
                 max_level: int = MAX_LEVEL,
                 clock: Callable[[], float] = time.monotonic):
        if down_burn >= up_burn:
            raise ValueError(f"hysteresis requires down_burn < up_burn "
                             f"({down_burn} >= {up_burn})")
        self.up_burn = up_burn
        self.down_burn = down_burn
        self.dwell_up = dwell_up
        self.dwell_down = dwell_down
        self.max_level = max_level
        self.clock = clock
        self.level = LEVEL_NORMAL
        self._last_change = float("-inf")
        self._calm_since: Optional[float] = None

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None,
                 clock: Callable[[], float] = time.monotonic
                 ) -> "BrownoutController":
        return cls(
            up_burn=_env_float("DYN_BROWNOUT_UP_BURN", 2.0, env),
            down_burn=_env_float("DYN_BROWNOUT_DOWN_BURN", 0.75, env),
            dwell_up=_env_float("DYN_BROWNOUT_DWELL_UP", 5.0, env),
            dwell_down=_env_float("DYN_BROWNOUT_DWELL_DOWN", 30.0, env),
            max_level=int(_env_float("DYN_BROWNOUT_MAX_LEVEL",
                                     MAX_LEVEL, env)),
            clock=clock)

    def update(self, burn: float, now: Optional[float] = None) -> int:
        now = self.clock() if now is None else now
        if burn >= self.up_burn:
            self._calm_since = None
            if (self.level < self.max_level
                    and now - self._last_change >= self.dwell_up):
                self.level += 1
                self._last_change = now
                log.warning("brownout step UP -> L%d (%s): burn %.2f",
                            self.level, LEVEL_NAMES[self.level], burn)
        elif burn <= self.down_burn and self.level > LEVEL_NORMAL:
            if self._calm_since is None:
                self._calm_since = now
            if now - self._calm_since >= self.dwell_down:
                self.level -= 1
                self._last_change = now
                self._calm_since = now
                log.info("brownout step DOWN -> L%d (%s): burn %.2f",
                         self.level, LEVEL_NAMES[self.level], burn)
        else:
            # the hysteresis band (down_burn, up_burn): hold, reset calm
            self._calm_since = None
        return self.level


# ---------------------------------------------------------------------------
# brownout store plane: the level is fleet state, not process state
# ---------------------------------------------------------------------------
BROWNOUT_PREFIX = "overload/"


def brownout_key(namespace: str) -> str:
    return f"{BROWNOUT_PREFIX}{namespace}/brownout"


async def publish_brownout(store, namespace: str, level: int,
                           burn: float = 0.0,
                           lease: Optional[int] = None) -> None:
    """Write the active level; lease-bound when the caller passes its lease
    so a dead controller's brownout expires instead of pinning the fleet
    degraded forever."""
    payload = json.dumps({"level": int(level),
                          "name": LEVEL_NAMES.get(int(level), "?"),
                          "burn": round(float(burn), 3),
                          "at": time.time()}).encode()
    await store.put(brownout_key(namespace), payload, lease=lease)


class BrownoutState:
    """A process's view of the fleet brownout level. Plain holder (level 0)
    until :meth:`watch` arms it against the store — frontends and routers
    read ``.level`` on every request with zero RPCs."""

    def __init__(self, level: int = LEVEL_NORMAL):
        self.level = int(level)

    async def watch(self, store, namespace: str) -> "BrownoutState":
        key = brownout_key(namespace)

        def apply(value: Optional[bytes], deleted: bool) -> None:
            if deleted or not value:
                self.level = LEVEL_NORMAL
                return
            try:
                self.level = int(json.loads(value.decode()).get("level", 0))
            except (ValueError, json.JSONDecodeError):
                log.warning("ignoring malformed brownout state: %r", value)

        async def on_change(k: str, value: Optional[bytes], deleted: bool):
            if k == key:
                apply(value, deleted)

        snapshot = await store.watch_prefix(key, on_change)
        for k, value in snapshot:
            if k == key:
                apply(value, False)
        return self


class BrownoutMonitor:
    """The standing controller: each tick reads the fleet's published
    stage-metric dumps, folds them through an ``SloMonitor``, steps the
    :class:`BrownoutController` on the worst burn, and publishes level
    changes to the store. Run it inside the planner (``--brownout``) or
    standalone (the overload soak drives :meth:`tick` directly)."""

    def __init__(self, store, namespace: str,
                 controller: Optional[BrownoutController] = None,
                 slo_monitor=None, lease: Optional[int] = None):
        from .slo import SloMonitor

        self.store = store
        self.namespace = namespace
        self.controller = controller or BrownoutController.from_env()
        # gauge=None: the brownout gauge below is the published series;
        # whoever also exports SLO burn does so via its own monitor
        self.slo = slo_monitor or SloMonitor(registry_gauge=None)
        self.lease = lease
        self._published: Optional[int] = None
        # tenant-scoped burn (DYN_TENANT_AVAILABILITY): one tenant's
        # server-fault failures step the ladder even when the fleet
        # aggregate still looks healthy — per-tenant SLOs are promises,
        # not averages
        obj = tenant_availability_objective()
        self.tenant_burn = TenantBurnTracker(obj) if obj else None

    async def apply(self, burn: float) -> int:
        """Step the controller on ``burn``, export the gauge, publish the
        level to the store when it changed (a failed publish retries on
        the next call). The ONE implementation of the level-publication
        protocol — the planner's ``--brownout`` path feeds its own burn
        signal through here too."""
        level = self.controller.update(burn)
        from .prometheus import stage_metrics

        stage_metrics().brownout_level.set(value=level)
        if level != self._published:
            try:
                await publish_brownout(self.store, self.namespace, level,
                                       burn, lease=self.lease)
                self._published = level
            except Exception:  # noqa: BLE001 - store mid-outage: retry next
                log.warning("brownout publish skipped", exc_info=True)
        return level

    async def tick(self, states=None) -> int:
        if states is None:
            from ..llm.metrics_aggregator import fetch_stage_states

            states = await fetch_stage_states(self.store, self.namespace)
        burns = self.slo.observe(states) if self.slo.objectives else {}
        burn = max((b for per_w in burns.values()
                    for b in per_w.values()), default=0.0)
        if self.tenant_burn is not None:
            self.tenant_burn.observe(states)
            burn = max(burn, self.tenant_burn.worst())
        return await self.apply(burn)

    async def run(self, interval: float = 1.0) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - one bad tick must not stop
                log.exception("brownout tick failed")
            await asyncio.sleep(interval)


# ---------------------------------------------------------------------------
# cluster-dump readers (dyntop / planner signals)
# ---------------------------------------------------------------------------
def _sum_counter(states, name: str) -> float:
    total = 0.0
    for _component, dump in states:
        st = dump.get(name)
        if not st or st.get("kind") != "counter":
            continue
        total += sum(st.get("series", {}).values())
    return total


def shed_totals(states) -> float:
    """Cumulative shed events across the fleet: admission rejects + stage
    queue sheds, summed over every published dump."""
    return (_sum_counter(states, "dyn_admission_rejects_total")
            + _sum_counter(states, "dyn_queue_shed_total"))


def admission_depth_total(states) -> float:
    """Sum of the per-frontend admission in-flight gauges."""
    total = 0.0
    for _component, dump in states:
        st = dump.get("dyn_admission_queue_depth")
        if not st or st.get("kind") != "gauge":
            continue
        total += sum(st.get("series", {}).values())
    return total


def brownout_level_from_states(states) -> int:
    """Worst published brownout level across dumps (the fleet level is a
    single store key, but each exporter mirrors it as a gauge)."""
    worst = 0
    for _component, dump in states:
        st = dump.get("dyn_brownout_level")
        if not st or st.get("kind") != "gauge":
            continue
        for v in st.get("series", {}).values():
            worst = max(worst, int(v))
    return worst
